"""Zero-downtime rolling rollout (serve/rollout.py) + multi-model
multiplexing: a registry version rolled across live replicas under load
with ZERO failed requests and zero mid-traffic compiles, token-identical
kept-session continuations vs an in-place-swap reference, mid-drain
replica death converging on the survivors, the drain-and-rejoin slot
RESIZE move (the autotuner's capacity leg), per-model routing, and the
canary shadow-diff report."""

import threading
import time

import jax
import pytest
from flax import serialization

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.serve import (
    ModelRegistry,
    RolloutError,
    ServeEngine,
    ServeServer,
    UnknownModelError,
)

_CFG = LMConfig(vocab_size=29, hidden_size=16, num_layers=1)


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(3), _CFG)


@pytest.fixture(scope="module")
def params_v2():
    return init_lm(jax.random.PRNGKey(99), _CFG)


def _registry(tmp_path, *versions):
    reg = ModelRegistry(str(tmp_path / "registry"))
    for payload in versions:
        reg.publish("default", serialization.to_bytes(payload))
    return reg


def _server(params, n, registry=None, rollout_kw=None, **kw):
    engines = [
        ServeEngine(params, _CFG, num_slots=4, prefill_buckets=(4, 8),
                    batch_buckets=(1, 2), rng_seed=i, replica=i)
        for i in range(n)
    ]
    kw.setdefault("max_active", 2)
    kw.setdefault("queue_size", 32)
    return ServeServer(engines if n > 1 else engines[0],
                       model_registry=registry,
                       rollout_kw=rollout_kw or {"drain_timeout_s": 20.0},
                       **kw)


def _total_compiles(server):
    return sum(sum(r.engine.compile_counts.values())
               for r in server.replicas)


# ---- rolling swap under load ------------------------------------------


def test_rolling_swap_under_load_zero_failures(tmp_path, params,
                                               params_v2):
    """The gate drill: continuous traffic across a 2-replica rolling
    reload sees ZERO failed requests and zero mid-traffic compiles; a
    kept session started on v1 continues token-identically to an
    in-place weight swap; fresh post-rollout requests decode the new
    version's tokens."""
    reg = _registry(tmp_path, params, params_v2)
    server = _server(params, 2, registry=reg)
    failures, done = [], threading.Event()

    def pump(worker):
        i = 0
        while not done.is_set():
            try:
                r = server.generate([1 + worker, 2, 3],
                                    max_new_tokens=2)
                if r.error is not None:
                    failures.append((worker, i, r.error))
            except Exception as e:  # queue-full would also be a failure:
                # capacity must stay >= N-1 replicas throughout
                failures.append((worker, i, repr(e)))
            i += 1

    with server:
        server.warmup()
        r1 = server.generate([1, 2, 3], max_new_tokens=4,
                             keep_session=True)
        sid, v1_toks = r1.session_id, list(r1.tokens)
        pumps = [threading.Thread(target=pump, args=(w,), daemon=True)
                 for w in range(3)]
        compiles_before = _total_compiles(server)
        for t in pumps:
            t.start()
        try:
            record = server.rollout.run_rollout("default", 2)
        finally:
            done.set()
            for t in pumps:
                t.join(timeout=30)
        assert record["outcome"] == "ok", record
        assert [p["outcome"] for e in record["replicas"]
                for p in e["phases"]] == ["ok"] * 8
        assert failures == [], failures[:5]
        # same-shape weight swap under an unchanged model id reuses every
        # compiled program: params are traced ARGUMENTS, not constants
        assert _total_compiles(server) == compiles_before
        assert all(r.engine.model_version == 2 for r in server.replicas)
        cont = server.generate([v1_toks[-1]], max_new_tokens=3,
                               session_id=sid, keep_session=True)
        post = server.generate([1, 2, 3], max_new_tokens=4)

    # reference: the same conversation on one replica with an IN-PLACE
    # swap (no drain, no migration) — the rolling path must match it
    ref = _server(params, 1)
    with ref:
        a = ref.generate([1, 2, 3], max_new_tokens=4, keep_session=True)
        assert list(a.tokens) == v1_toks
        ref.engine.swap_model(jax.device_get(params_v2), version=2)
        b = ref.generate([v1_toks[-1]], max_new_tokens=3,
                         session_id=a.session_id, keep_session=True)
        c = ref.generate([1, 2, 3], max_new_tokens=4)
    assert list(cont.tokens) == list(b.tokens)
    assert list(post.tokens) == list(c.tokens)


def test_mid_drain_replica_death_converges(tmp_path, params, params_v2):
    """Chaos: the drainee dies mid-drain. The controller hands the corpse
    to the normal death path (end_drain + sweep → retire/requeue/migrate)
    and keeps rolling — every SURVIVING replica still converges to the
    new version. Three replicas so the capacity invariant (never drain
    the last routable) holds even after losing one."""
    reg = _registry(tmp_path, params, params_v2)
    server = _server(params, 3, registry=reg)
    with server:
        server.warmup()
        rep = server.replicas[0]
        # kill the scheduler thread, then pin load() > 0 so the drain
        # loop observes a dead-but-not-quiesced replica
        boom = RuntimeError("injected scheduler crash")
        rep.batcher.step = (  # type: ignore[method-assign]
            lambda: (_ for _ in ()).throw(boom))
        rep.thread.join(timeout=10)  # the idle loop trips it immediately
        assert not rep.thread.is_alive()
        rep.batcher.load = lambda: 1  # type: ignore[method-assign]
        record = server.rollout.run_rollout("default", 2)
        assert record["replicas"][0]["phases"] == [
            {"phase": "drain", "outcome": "died"}]
        for entry in record["replicas"][1:]:
            assert [p["outcome"] for p in entry["phases"]] == ["ok"] * 4
        assert rep.retired  # swept into the normal retire path
        live = [r for r in server.replicas if r.routable()]
        assert len(live) == 2
        assert all(r.engine.model_version == 2 for r in live)
        r = server.generate([1, 2, 3], max_new_tokens=2)
        assert r.error is None and r.replica in (1, 2)


# ---- the resize move ---------------------------------------------------


def test_resize_move_recompiles_off_path(tmp_path, params):
    """Slot-count resize is a drain-and-rejoin move: new cache shapes are
    re-warmed BEFORE rejoin (compiles happen, but off-path), kept
    sessions survive via migration, and admission re-clamps to the new
    capacity."""
    reg = _registry(tmp_path, params)
    server = _server(params, 2, registry=reg)
    with server:
        server.warmup()
        r1 = server.generate([1, 2, 3], max_new_tokens=2,
                             keep_session=True)
        record = server.rollout.run_resize(8)
        assert record["outcome"] == "ok"
        assert all(r.engine.cache.num_slots == 8
                   for r in server.replicas)
        assert all(r.batcher.max_active == 8 for r in server.replicas)
        # the v1 session survived two consecutive drains
        cont = server.generate([r1.tokens[-1]], max_new_tokens=2,
                               session_id=r1.session_id)
        assert cont.error is None
        # idempotent: already at target → no drains at all
        again = server.rollout.run_resize(8)
        assert again["replicas"] == []
    assert server.rollout.stats()["resizes"] == 2


def test_autotuner_requested_resize_lands_async(tmp_path, params):
    """request_resize is the autotuner's entry point: the controller
    thread (started with the server) picks the queued move up and
    applies it without any caller-side orchestration."""
    reg = _registry(tmp_path, params)
    server = _server(params, 2, registry=reg,
                     rollout_kw={"drain_timeout_s": 20.0,
                                 "interval_s": 0.02})
    with server:
        server.warmup()
        assert server.rollout.stats()["running"]
        server.rollout.request_resize(8)
        deadline = time.monotonic() + 60
        while (any(r.engine.cache.num_slots != 8
                   for r in server.replicas)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert all(r.engine.cache.num_slots == 8
                   for r in server.replicas)
        assert server.generate([1, 2], max_new_tokens=1).error is None


# ---- multi-model multiplexing -----------------------------------------


def test_multi_model_routing_and_404(params, params_v2):
    """Two models resident on one fleet: requests route by their
    ``model`` field (token-identical to a single-model fleet of that
    model), kept sessions stay on their model, the default stays
    untouched, and an unknown model 404s loudly."""
    server = _server(params, 2)
    ref = _server(params_v2, 1)
    with server, ref:
        for rep in server.replicas:
            rep.engine.add_model("exp", jax.device_get(params_v2),
                                 version=7)
        server.warmup()  # warms BOTH residents' lattices
        ref.warmup()
        want = ref.generate([1, 2, 3], max_new_tokens=4)
        got = server.generate([1, 2, 3], max_new_tokens=4, model="exp",
                              keep_session=True)
        assert list(got.tokens) == list(want.tokens)
        base = server.generate([1, 2, 3], max_new_tokens=4)
        assert list(base.tokens) != list(got.tokens)
        # a continuation carries its model across windows
        cont = server.generate([got.tokens[-1]], max_new_tokens=2,
                               session_id=got.session_id, model="exp")
        assert cont.error is None
        with pytest.raises(UnknownModelError, match="ghost"):
            server.generate([1, 2], max_new_tokens=1, model="ghost")
        models = server.stats()["models"]
        assert models["exp"] == {"7": 2}
        assert sorted(models) == ["default", "exp"]


# ---- canary ------------------------------------------------------------


def _with_traffic(server, fn):
    """Run ``fn`` while stateless traffic flows (the canary needs pairs
    to shadow)."""
    done, failures = threading.Event(), []

    def pump():
        while not done.is_set():
            try:
                r = server.generate([1, 2, 3], max_new_tokens=2)
                if r.error is not None:
                    failures.append(r.error)
            except Exception as e:
                failures.append(repr(e))

    threads = [threading.Thread(target=pump, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        return fn()
    finally:
        done.set()
        for t in threads:
            t.join(timeout=30)
        assert failures == [], failures[:5]


def test_canary_match_report(tmp_path, params):
    """Rolling to a version with IDENTICAL weights: every shadow pair
    token-matches, the report says so, and promotion proceeds even under
    require_canary_match."""
    reg = _registry(tmp_path, params, params)  # v2 == v1 bytes
    server = _server(params, 2, registry=reg,
                     rollout_kw={"drain_timeout_s": 20.0,
                                 "canary_every": 1,
                                 "canary_min_pairs": 2,
                                 "canary_timeout_s": 30.0,
                                 "require_canary_match": True})
    with server:
        server.warmup()
        record = _with_traffic(
            server, lambda: server.rollout.run_rollout("default", 2))
        report = record["canary"]
        assert record["outcome"] == "ok"
        assert report["verdict"] == "match"
        assert report["counts"]["compared"] >= 2
        assert report["counts"]["diff"] == 0
        assert report["slo"]["primary"]["count"] >= 2
        assert server.rollout.stats()["last_canary"] == report


def test_canary_regression_aborts_promotion(tmp_path, params, params_v2):
    """Rolling to genuinely different weights under require_canary_match:
    the shadow pairs diff, the rollout aborts as 'canary_regression', and
    the NON-canary replica keeps serving the old version."""
    reg = _registry(tmp_path, params, params_v2)
    server = _server(params, 2, registry=reg,
                     rollout_kw={"drain_timeout_s": 20.0,
                                 "canary_every": 1,
                                 "canary_min_pairs": 2,
                                 "canary_timeout_s": 30.0,
                                 "require_canary_match": True})
    with server:
        server.warmup()
        with pytest.raises(RolloutError, match="aborting promotion"):
            _with_traffic(
                server,
                lambda: server.rollout.run_rollout("default", 2))
        record = server.rollout.stats()["history"][-1]
        assert record["outcome"] == "canary_regression"
        assert record["canary"]["counts"]["diff"] > 0
        # capacity is intact: the canary replica rejoined (on v2, kept
        # for diagnosis), the primary never left its boot version (the
        # engine starts at ctor-default version 0 — v1 was never rolled)
        assert server.replicas[0].engine.model_version == 0
        assert server.replicas[1].engine.model_version == 2
        assert all(r.routable() for r in server.replicas)
        assert server.generate([4, 5], max_new_tokens=1).error is None
