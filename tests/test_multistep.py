"""K-steps-per-dispatch tests (train/multistep.py): the scanned K-step
program must be exactly K iterations of the shared single-step body — parity
against sequential single steps, single-chip and DP, stateless and stateful,
plus the host-side stacking/prefetch feed and the CLI path."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.data import prefetch_to_device, stacked_batches
from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.models.lstm_lm import init_carries
from lstm_tensorspark_tpu.parallel import make_mesh, shard_batch
from lstm_tensorspark_tpu.parallel.data_parallel import replicate
from lstm_tensorspark_tpu.train import (
    make_dp_multi_train_step,
    make_multi_train_step,
    make_optimizer,
    make_train_step,
)
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T, K = 11, 16, 8, 12, 4


def _setup(stateful=False):
    cfg = LMConfig(vocab_size=V, hidden_size=H)

    if stateful:

        def loss_fn(params, batch, rng, carries):
            return lm_loss(params, batch, cfg, carries=carries)

    else:

        def loss_fn(params, batch, rng):
            return lm_loss(params, batch, cfg)

    opt = make_optimizer("momentum", 0.3, momentum=0.9)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batches = [
        {
            "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
            "targets": rng.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(K)
    ]
    stacked = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    return cfg, loss_fn, opt, params, batches, stacked


def _tree_close(a, b, tol=1e-6):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=tol, rtol=tol)


def test_multistep_matches_sequential_single_steps():
    cfg, loss_fn, opt, params, batches, stacked = _setup()

    single = make_train_step(loss_fn, opt)
    s1 = init_train_state(params, opt, jax.random.PRNGKey(1))
    losses = []
    for b in batches:
        s1, m = single(s1, b)
        losses.append(float(m["loss"]))

    multi = make_multi_train_step(loss_fn, opt)
    s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
    s2, mm = multi(s2, stacked)

    assert int(s2.step) == K == int(s1.step)
    _tree_close(s1.params, s2.params)
    np.testing.assert_allclose(float(mm["loss"]), np.mean(losses), atol=1e-6)
    np.testing.assert_allclose(float(mm["loss_last"]), losses[-1], atol=1e-6)


def test_multistep_stateful_carries_thread_through_scan():
    cfg, loss_fn, opt, params, batches, stacked = _setup(stateful=True)

    single = make_train_step(loss_fn, opt, stateful=True)
    s1 = init_train_state(
        params, opt, jax.random.PRNGKey(1), carries=init_carries(cfg, B)
    )
    for b in batches:
        s1, _ = single(s1, b)

    multi = make_multi_train_step(loss_fn, opt, stateful=True)
    s2 = init_train_state(
        params, opt, jax.random.PRNGKey(1), carries=init_carries(cfg, B)
    )
    s2, _ = multi(s2, stacked)

    _tree_close(s1.params, s2.params)
    _tree_close(s1.carries, s2.carries)


def test_dp_multistep_matches_single_device_multistep():
    cfg, loss_fn, opt, params, batches, stacked = _setup()

    multi = make_multi_train_step(loss_fn, opt)
    s1 = init_train_state(params, opt, jax.random.PRNGKey(1))
    s1, m1 = multi(s1, stacked)

    mesh = make_mesh(dp=8)
    dp_multi = make_dp_multi_train_step(loss_fn, opt, mesh)
    s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
    s2 = s2._replace(params=replicate(s2.params, mesh),
                     opt_state=replicate(s2.opt_state, mesh))
    s2, m2 = dp_multi(s2, shard_batch(stacked, mesh, dim=1))

    assert int(s2.step) == K
    _tree_close(s1.params, s2.params, tol=1e-5)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), atol=1e-5)


def test_stacked_batches_and_prefetch_feed():
    rng = np.random.RandomState(0)
    stream = (
        {"inputs": rng.randint(0, V, (B, T)).astype(np.int32)} for _ in range(7)
    )
    chunks = list(prefetch_to_device(stacked_batches(stream, 3)))
    assert len(chunks) == 2  # trailing partial group of 1 dropped
    assert chunks[0]["inputs"].shape == (3, B, T)
    assert isinstance(chunks[0]["inputs"], jax.Array)


def test_prefetch_abandoned_consumer_stops_producer():
    import threading
    import time

    produced = []

    def infinite():
        i = 0
        while True:
            produced.append(i)
            yield {"x": np.full((2,), i, np.float32)}
            i += 1

    it = prefetch_to_device(infinite(), size=2)
    next(it)
    it.close()  # abandon mid-stream → producer must quit, queue drain
    n_after_close = len(produced)
    time.sleep(0.2)
    # producer made no further progress beyond the item it may have been
    # blocked on when the consumer vanished
    assert len(produced) <= n_after_close + 1
    assert not any(
        t.is_alive() and t.daemon and "producer" in repr(t.name)
        for t in threading.enumerate()
        if t.name.startswith("prefetch")
    )


def test_prefetch_propagates_producer_errors():
    def bad():
        yield {"x": np.zeros((2,), np.float32)}
        raise ValueError("boom")

    it = prefetch_to_device(bad())
    next(it)
    try:
        next(it)
        assert False, "expected ValueError"
    except ValueError as e:
        assert "boom" in str(e)


def test_cli_steps_per_call_e2e(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "m.jsonl"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "16", "--batch-size", "8",
        "--seq-len", "16", "--num-steps", "8", "--steps-per-call", "4",
        "--log-every", "1", "--jsonl", str(jsonl), "--backend", "dp",
        "--num-partitions", "4",
    ])
    assert rc == 0
    import json

    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    steps = [r["step"] for r in records if "loss" in r and "step" in r]
    assert steps and steps[-1] == 8  # 2 calls x 4 steps
