"""Integration tests (SURVEY.md §4): tiny-corpus overfit reaching low loss in
seconds; CLI end-to-end through the DP backend; checkpoint resume."""

import json
import os

import jax
import numpy as np

from lstm_tensorspark_tpu.data import lm_batch_stream
from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state, train_loop


def test_overfit_tiny_corpus():
    """A 1-layer LSTM must drive next-char loss well below the unigram
    entropy on a tiny repeating corpus — end-to-end learning signal check."""
    text = "abcdefgh" * 200
    vocab = sorted(set(text))
    tokens = np.asarray([vocab.index(c) for c in text], np.int32)
    cfg = LMConfig(vocab_size=len(vocab), hidden_size=32)

    def loss_fn(params, batch, rng):
        return lm_loss(params, batch, cfg)

    opt = make_optimizer("adam", 1e-2)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    step = make_train_step(loss_fn, opt)

    batches = lm_batch_stream(tokens, batch_size=4, seq_len=16)
    first = None
    for i, b in enumerate(batches):
        state, m = step(state, b)
        if first is None:
            first = float(m["loss"])
        if i >= 150:
            break
    final = float(m["loss"])
    assert first > 1.5  # ~log(8) at init
    assert final < 0.1, f"failed to overfit: {final}"


def test_cli_end_to_end_dp(tmp_path):
    """Full CLI run on the 8-device CPU mesh: DP backend, metrics JSONL,
    checkpointing."""
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "metrics.jsonl"
    ckpt = tmp_path / "ckpt"
    rc = main([
        "--dataset", "ptb_char",
        "--hidden-units", "32",
        "--batch-size", "16",
        "--seq-len", "16",
        "--num-steps", "12",
        "--log-every", "4",
        "--learning-rate", "0.5",
        "--compute-dtype", "float32",
        "--jsonl", str(jsonl),
        "--checkpoint-dir", str(ckpt),
        "--checkpoint-every", "10",
    ])
    assert rc == 0
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    start = next(r for r in records if r.get("note") == "start")
    assert start["backend"] == "dp" and start["partitions"] == 8
    losses = [r["loss"] for r in records if "loss" in r]
    assert losses and all(np.isfinite(losses))
    assert any(r.get("note") == "final" and "eval_ppl" in r for r in records)
    assert os.path.exists(ckpt / "step_10.msgpack")


def test_cli_resume(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    common = [
        "--dataset", "ptb_char", "--hidden-units", "16",
        "--batch-size", "8", "--seq-len", "8", "--log-every", "0",
        "--compute-dtype", "float32",
        "--checkpoint-dir", str(tmp_path / "ck"), "--checkpoint-every", "5",
        "--jsonl", str(tmp_path / "m.jsonl"),
    ]
    assert main(common + ["--num-steps", "5"]) == 0
    # --num-steps is the TOTAL budget: resuming at 5 with budget 8 runs 3 more
    assert main(common + ["--num-steps", "8", "--resume"]) == 0
    records = [json.loads(l) for l in (tmp_path / "m.jsonl").read_text().splitlines()]
    assert any("resumed at step 5" in str(r.get("note", "")) for r in records)
    finals = [r for r in records if r.get("note") == "final"]
    assert finals[-1]["step"] == 8


def test_cli_classifier_dp(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "c.jsonl"
    rc = main([
        "--dataset", "imdb", "--hidden-units", "16", "--batch-size", "16",
        "--seq-len", "32", "--num-steps", "6", "--log-every", "3",
        "--optimizer", "adam", "--learning-rate", "1e-3",
        "--compute-dtype", "float32", "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    start = next(r for r in records if r.get("note") == "start")
    assert start["backend"] == "dp"
    final = next(r for r in records if r.get("note") == "final")
    assert "eval_accuracy" in final and np.isfinite(final["eval_loss"])


def test_cli_forecaster_dp(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "f.jsonl"
    rc = main([
        "--dataset", "uci_electricity", "--hidden-units", "16",
        "--batch-size", "16", "--seq-len", "48", "--num-steps", "6",
        "--log-every", "3", "--optimizer", "adam", "--learning-rate", "1e-3",
        "--compute-dtype", "float32", "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    final = next(r for r in records if r.get("note") == "final")
    assert np.isfinite(final["eval_mse"])


def test_cli_tp_sp(tmp_path):
    """CLI with --tensor-parallel/--seq-parallel on the 8-device mesh."""
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "m.jsonl"
    rc = main([
        "--dataset", "ptb_char",
        "--hidden-units", "32",
        "--batch-size", "16",
        "--seq-len", "16",
        "--num-steps", "6",
        "--log-every", "3",
        "--learning-rate", "0.5",
        "--compute-dtype", "float32",
        "--tensor-parallel", "2",
        "--seq-parallel", "2",
        "--eval-every", "6",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    start = next(r for r in records if r.get("note") == "start")
    assert start["mesh"] == {"dp": 2, "tp": 2, "sp": 2, "pp": 1}
    losses = [r["loss"] for r in records if "loss" in r]
    assert losses and all(np.isfinite(losses))
    assert any(r.get("note") == "final" and "eval_ppl" in r for r in records)


def test_cli_pipeline(tmp_path):
    """CLI with --pipeline-stages (DP x PP) incl. checkpoint + resume of the
    stage-sharded state."""
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "m.jsonl"
    ckpt = tmp_path / "ckpt"
    common = [
        "--dataset", "ptb_char",
        "--hidden-units", "32",
        "--num-layers", "2",
        "--batch-size", "16",
        "--seq-len", "16",
        "--log-every", "3",
        "--learning-rate", "0.5",
        "--compute-dtype", "float32",
        "--pipeline-stages", "2",
        "--jsonl", str(jsonl),
        "--checkpoint-dir", str(ckpt),
        "--checkpoint-every", "3",
    ]
    assert main(common + ["--num-steps", "3"]) == 0
    assert main(common + ["--num-steps", "6", "--resume"]) == 0
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    start = next(r for r in records if r.get("note") == "start")
    assert start["mesh"]["pp"] == 2 and start["backend"] == "pp"
    assert any("resumed at step 3" in str(r.get("note", "")) for r in records)
    finals = [r for r in records if r.get("note") == "final"]
    assert finals and all(np.isfinite(f["eval_ppl"]) for f in finals)


def test_eval_only_zero_step_budget(tmp_path):
    """Explicit --num-steps 0 + --resume = the eval-only recipe: NO
    training steps run (0 is not 'unset'), just the final eval at the
    restored step."""
    import json

    from lstm_tensorspark_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    jsonl = tmp_path / "m.jsonl"
    argv = [
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--backend", "single",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
    ]
    assert main(argv + ["--num-steps", "4"]) == 0
    assert main(argv + ["--num-steps", "0", "--resume",
                        "--jsonl", str(jsonl)]) == 0
    records = [json.loads(l) for l in open(jsonl)]
    final = [r for r in records if r.get("note") == "final"][0]
    assert final["step"] == 4, final
    assert "eval_ppl" in final
