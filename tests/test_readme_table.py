"""tools/readme_table.py: the generated five-config perf table — vintage
line prefers the table's own provenance stamp, rendering is stable, and
the committed README is in sync with BENCH_TABLE.json."""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import readme_table  # noqa: E402


def test_vintage_prefers_table_stamp():
    """A freshly-written (possibly uncommitted) table must be attributed
    to ITS OWN captured_at/measured_at_commit, not to the git history of
    the previous measurement."""
    line = readme_table._vintage({
        "captured_at": "2026-08-02T10:00:00+00:00",
        "measured_at_commit": "abc1234",
    })
    assert "2026-08-02" in line
    assert "abc1234" in line


def test_vintage_stampless_table_falls_back_to_git(monkeypatch):
    """Pre-r5 tables without the stamp fall back to the committed file's
    git history — deterministic via a stubbed `git log` so a broken
    fallback can't hide behind the empty no-git return."""
    import subprocess  # _vintage imports the module locally — same object

    class _Out:
        stdout = "abc1234 2026-01-01\n"

    monkeypatch.setattr(subprocess, "run", lambda *a, **k: _Out())
    line = readme_table._vintage({})
    assert "abc1234" in line and "2026-01-01" in line

    # and a failing git still degrades to the empty line, not a crash
    def _boom(*a, **k):
        raise OSError("no git")

    monkeypatch.setattr(subprocess, "run", _boom)
    assert readme_table._vintage({}) == ""


def test_render_marks_unmeasured_configs():
    table = {
        "configs": {
            "ptb_char": {
                "kind": "lm",
                "dims": {"V": 50, "H": 128, "L": 1, "B": 64, "T": 64},
                "seq_per_sec": 756308.69, "tokens_per_sec": 48403755.9,
                "model_tflops_per_sec": 39.925, "mfu_vs_bf16_peak": 0.2027,
                "roofline": {"fraction_of_bound": 0.5027},
            },
            "wikitext2": {"error": "wedged"},
        },
    }
    out = readme_table.render(table)
    row1 = next(l for l in out.splitlines() if "PTB char" in l)
    assert "756.3k seq/s" in row1 and "20.3%" in row1 and "50%" in row1
    row3 = next(l for l in out.splitlines() if "WikiText-2" in l)
    assert "not measured" in row3 and "wedged" in row3


def test_committed_readme_in_sync():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "tools/readme_table.py", "--check"],
        capture_output=True, text=True, cwd=repo, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    json.load(open(os.path.join(repo, "BENCH_TABLE.json")))
