"""Fused train+eval executable (train/device_step.py): the lax.cond-gated
on-device eval must equal the host-driven `evaluate()` exactly, and
non-eval calls must be bit-identical to the plain device-data step."""

import json

import jax
import numpy as np

from lstm_tensorspark_tpu.data import (
    lm_epoch_batches,
    stage_lm_data,
    window_index_stream,
)
from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.models.lstm_lm import init_carries
from lstm_tensorspark_tpu.parallel import make_mesh, shard_batch
from lstm_tensorspark_tpu.parallel.data_parallel import replicate
from lstm_tensorspark_tpu.train import (
    make_device_dp_lm_train_step,
    make_device_lm_train_step,
    make_eval_step,
    make_optimizer,
)
from lstm_tensorspark_tpu.train.loop import evaluate, init_train_state

B, T, V, H, K = 8, 16, 29, 16, 4


def _tokens(n, seed=0):
    return np.random.RandomState(seed).randint(0, V, n).astype(np.int32)


def _setup(stateful=False):
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)

    if stateful:

        def loss_fn(p, b, r, carries):
            return lm_loss(p, b, cfg, carries=carries)

    else:

        def loss_fn(p, b, r):
            return lm_loss(p, b, cfg)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    train_tokens = _tokens(B * T * 8 + 1)
    valid_tokens = _tokens(B * T * 3 + 1, seed=1)
    carries0 = init_carries(cfg, B) if stateful else None
    state = init_train_state(params, opt, jax.random.PRNGKey(1), carries=carries0)
    return cfg, loss_fn, opt, state, train_tokens, valid_tokens


def test_fused_eval_matches_host_evaluate():
    cfg, loss_fn, opt, state, train_tokens, valid_tokens = _setup()
    ddata = stage_lm_data(train_tokens, B, T)
    edata = stage_lm_data(valid_tokens, B, T)
    step = make_device_lm_train_step(
        loss_fn, opt, ddata, eval_data=edata, steps_per_call=K
    )
    state, ms = step(state, ddata.arrays, np.int32(0), edata.arrays,
                     np.bool_(True))
    # host-driven eval on the SAME post-update params
    host = evaluate(
        make_eval_step(loss_fn), state.params,
        lm_epoch_batches(valid_tokens, B, T),
    )
    np.testing.assert_allclose(
        float(ms["eval_loss"]), host["eval_loss"], rtol=1e-6
    )


def test_fused_no_eval_is_bit_identical_to_plain_step():
    cfg, loss_fn, opt, state, train_tokens, valid_tokens = _setup()
    ddata = stage_lm_data(train_tokens, B, T)
    edata = stage_lm_data(valid_tokens, B, T)
    fused = make_device_lm_train_step(
        loss_fn, opt, ddata, eval_data=edata, steps_per_call=K
    )
    plain = make_device_lm_train_step(loss_fn, opt, ddata, steps_per_call=K)

    sf, mf = fused(state, ddata.arrays, np.int32(0), edata.arrays,
                   np.bool_(False))
    sp, mp = plain(state, ddata.arrays, np.int32(0))
    assert np.isnan(float(mf["eval_loss"]))
    np.testing.assert_array_equal(np.asarray(mf["loss"]), np.asarray(mp["loss"]))
    for a, b in zip(jax.tree.leaves(sf.params), jax.tree.leaves(sp.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_eval_windows_cap():
    cfg, loss_fn, opt, state, train_tokens, valid_tokens = _setup()
    ddata = stage_lm_data(train_tokens, B, T)
    edata = stage_lm_data(valid_tokens, B, T)
    assert edata.n_windows >= 2
    step = make_device_lm_train_step(
        loss_fn, opt, ddata, eval_data=edata, steps_per_call=K, eval_windows=1
    )
    state, ms = step(state, ddata.arrays, np.int32(0), edata.arrays,
                     np.bool_(True))
    from lstm_tensorspark_tpu.data.batching import cap_batches

    host = evaluate(
        make_eval_step(loss_fn), state.params,
        cap_batches(lm_epoch_batches(valid_tokens, B, T), 1),
    )
    np.testing.assert_allclose(
        float(ms["eval_loss"]), host["eval_loss"], rtol=1e-6
    )


def test_fused_eval_stateful_matches_host():
    cfg, loss_fn, opt, state, train_tokens, valid_tokens = _setup(stateful=True)
    ddata = stage_lm_data(train_tokens, B, T)
    edata = stage_lm_data(valid_tokens, B, T)
    step = make_device_lm_train_step(
        loss_fn, opt, ddata, eval_data=edata, steps_per_call=K, stateful=True
    )
    ev_carries0 = init_carries(cfg, B)
    state, ms = step(state, ddata.arrays, np.int32(0), edata.arrays,
                     np.bool_(True), ev_carries0)
    host = evaluate(
        make_eval_step(loss_fn, stateful=True), state.params,
        lm_epoch_batches(valid_tokens, B, T),
        carries=init_carries(cfg, B),
    )
    np.testing.assert_allclose(
        float(ms["eval_loss"]), host["eval_loss"], rtol=1e-6
    )


def test_fused_eval_dp_matches_single():
    cfg, loss_fn, opt, state, train_tokens, valid_tokens = _setup()
    mesh = make_mesh(dp=8)
    ddata_s = stage_lm_data(train_tokens, B, T)
    edata_s = stage_lm_data(valid_tokens, B, T)
    single = make_device_lm_train_step(
        loss_fn, opt, ddata_s, eval_data=edata_s, steps_per_call=K
    )
    s1, m1 = single(state, ddata_s.arrays, np.int32(0), edata_s.arrays,
                    np.bool_(True))

    ddata = stage_lm_data(train_tokens, B, T, mesh=mesh)
    edata = stage_lm_data(valid_tokens, B, T, mesh=mesh)
    dp = make_device_dp_lm_train_step(
        loss_fn, opt, ddata, mesh, eval_data=edata, steps_per_call=K
    )
    state_dp = state._replace(
        params=replicate(state.params, mesh),
        opt_state=replicate(state.opt_state, mesh),
    )
    s2, m2 = dp(state_dp, ddata.arrays, np.int32(0), edata.arrays,
                np.bool_(True), None)
    # same global batch, same windows → same training and same eval value
    np.testing.assert_allclose(
        float(m1["eval_loss"]), float(m2["eval_loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(m1["loss"]), float(m2["loss"]), rtol=1e-5
    )


def test_cli_fused_eval_end_to_end(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "m.jsonl"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--num-steps", "8",
        "--steps-per-call", "2", "--device-data", "--fused-eval",
        "--eval-every", "2", "--log-every", "1", "--backend", "single",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    evals = [r for r in records if "eval_ppl" in r and r.get("note") != "final"]
    assert len(evals) >= 2, records
    assert all(np.isfinite(r["eval_ppl"]) for r in evals)
    # the final record comes from the HOST eval path on the same params —
    # the two implementations cross-check each other at the last eval step
    final = [r for r in records if r.get("note") == "final"][0]
    last = [r for r in evals if r["step"] == final["step"]]
    assert last, (evals, final)  # a fused eval MUST land on the final step
    np.testing.assert_allclose(
        last[0]["eval_loss"], final["eval_loss"], rtol=1e-5
    )


def test_cli_fused_eval_classifier_matches_host_final(tmp_path):
    """The classifier's fused eval and its host eval_fn share the last step's
    params (the 'final' record) — they must agree to float tolerance."""
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "c.jsonl"
    rc = main([
        "--dataset", "imdb", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "32", "--num-steps", "6",
        "--steps-per-call", "2", "--device-data", "--fused-eval",
        "--eval-every", "3", "--log-every", "1", "--backend", "single",
        "--learning-rate", "0.1", "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    evals = [r for r in records
             if "eval_accuracy" in r and r.get("note") != "final"]
    assert evals, records
    final = [r for r in records if r.get("note") == "final"][0]
    last = [r for r in evals if r["step"] == final["step"]]
    assert last, (evals, final)
    np.testing.assert_allclose(
        last[0]["eval_loss"], final["eval_loss"], rtol=1e-5
    )
    np.testing.assert_allclose(
        last[0]["eval_accuracy"], final["eval_accuracy"], rtol=1e-5
    )


def test_cli_fused_eval_forecaster_matches_host_final(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "f.jsonl"
    rc = main([
        "--dataset", "uci_electricity", "--hidden-units", "16",
        "--num-layers", "1", "--batch-size", "8", "--seq-len", "24",
        "--num-steps", "6", "--steps-per-call", "2", "--device-data",
        "--fused-eval", "--eval-every", "3", "--log-every", "1",
        "--backend", "single", "--learning-rate", "0.05",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    evals = [r for r in records if "eval_mse" in r and r.get("note") != "final"]
    assert evals, records
    final = [r for r in records if r.get("note") == "final"][0]
    last = [r for r in evals if r["step"] == final["step"]]
    assert last, (evals, final)
    np.testing.assert_allclose(last[0]["eval_mse"], final["eval_mse"],
                               rtol=1e-4)
    np.testing.assert_allclose(last[0]["eval_mae"], final["eval_mae"],
                               rtol=1e-4)


def test_cli_fused_eval_dp_classifier(tmp_path):
    """Fused eval under the DP backend (replicated eval batches) runs and
    logs finite metrics on the 8-device mesh."""
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "d.jsonl"
    rc = main([
        "--dataset", "imdb", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "16", "--seq-len", "32", "--num-steps", "4",
        "--steps-per-call", "2", "--device-data", "--fused-eval",
        "--eval-every", "2", "--log-every", "1", "--backend", "dp",
        "--num-partitions", "8", "--learning-rate", "0.1",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    evals = [r for r in records
             if "eval_accuracy" in r and r.get("note") != "final"]
    assert evals and all(np.isfinite(r["eval_accuracy"]) for r in evals)


def test_cli_fused_eval_host_fed_lm_matches_device_data(tmp_path):
    """--fused-eval without --device-data (host-fed train feed, staged eval
    stream): must produce the SAME eval records as the device-data run —
    identical data order (tests/test_device_data.py) + identical eval."""
    from lstm_tensorspark_tpu.cli import main

    argv = [
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--num-steps", "8",
        "--steps-per-call", "2", "--fused-eval", "--eval-every", "2",
        "--log-every", "1", "--backend", "single",
    ]
    a, b = tmp_path / "host.jsonl", tmp_path / "dev.jsonl"
    assert main(argv + ["--jsonl", str(a)]) == 0
    assert main(argv + ["--device-data", "--jsonl", str(b)]) == 0

    def evals(p):
        return [(r["step"], r["eval_loss"]) for r in map(json.loads, open(p))
                if "eval_loss" in r]

    ea, eb = evals(a), evals(b)
    assert ea and [s for s, _ in ea] == [s for s, _ in eb]
    np.testing.assert_allclose([v for _, v in ea], [v for _, v in eb],
                               rtol=1e-6)


def test_cli_fused_eval_host_fed_k1_single_step(tmp_path):
    """Host-fed fused eval at --steps-per-call 1 (the K=1 stacked path)."""
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "k1.jsonl"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--num-steps", "4",
        "--fused-eval", "--eval-every", "2", "--log-every", "1",
        "--backend", "single", "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    evals = [r for r in records if "eval_ppl" in r and r.get("note") != "final"]
    final = [r for r in records if r.get("note") == "final"][0]
    last = [r for r in evals if r["step"] == final["step"]]
    assert last, (evals, final)
    np.testing.assert_allclose(last[0]["eval_loss"], final["eval_loss"],
                               rtol=1e-5)


def test_cli_fused_eval_host_fed_forecaster_dp(tmp_path):
    """Host-fed fused eval for a task runner under the DP backend."""
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "fdp.jsonl"
    rc = main([
        "--dataset", "uci_electricity", "--hidden-units", "16",
        "--num-layers", "1", "--batch-size", "16", "--seq-len", "24",
        "--num-steps", "4", "--steps-per-call", "2", "--fused-eval",
        "--eval-every", "2", "--log-every", "1", "--backend", "dp",
        "--num-partitions", "8", "--learning-rate", "0.05",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    evals = [r for r in records if "eval_mse" in r and r.get("note") != "final"]
    final = [r for r in records if r.get("note") == "final"][0]
    last = [r for r in evals if r["step"] == final["step"]]
    assert last, (evals, final)
    np.testing.assert_allclose(last[0]["eval_mse"], final["eval_mse"],
                               rtol=1e-4)


def test_cli_fused_eval_rejected_with_tp():
    import pytest

    from lstm_tensorspark_tpu.cli import main

    with pytest.raises(SystemExit):
        main([
            "--dataset", "ptb_char", "--num-steps", "2", "--fused-eval",
            "--tensor-parallel", "2",
        ])


def test_cli_fused_eval_requires_eval_cadence():
    import pytest

    from lstm_tensorspark_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["--dataset", "ptb_char", "--num-steps", "2", "--fused-eval"])


def test_cli_fused_eval_tp_classifier(tmp_path):
    """Fused eval under --tensor-parallel (GSPMD jit step + gated eval tail):
    fused and host evals must agree on the shared final step."""
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "tpc.jsonl"
    rc = main([
        "--dataset", "imdb", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "16", "--seq-len", "32", "--num-steps", "4",
        "--fused-eval", "--eval-every", "2", "--log-every", "1",
        "--tensor-parallel", "2", "--num-partitions", "2",
        "--learning-rate", "0.1", "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    evals = [r for r in records
             if "eval_accuracy" in r and r.get("note") != "final"]
    final = [r for r in records if r.get("note") == "final"][0]
    last = [r for r in evals if r["step"] == final["step"]]
    assert last, (evals, final)
    np.testing.assert_allclose(last[0]["eval_loss"], final["eval_loss"],
                               rtol=1e-5)
    np.testing.assert_allclose(last[0]["eval_accuracy"],
                               final["eval_accuracy"], rtol=1e-5)


def test_cli_fused_eval_tp_forecaster(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "tpf.jsonl"
    rc = main([
        "--dataset", "uci_electricity", "--hidden-units", "16",
        "--num-layers", "1", "--batch-size", "16", "--seq-len", "24",
        "--num-steps", "4", "--fused-eval", "--eval-every", "2",
        "--log-every", "1", "--tensor-parallel", "2",
        "--num-partitions", "2", "--learning-rate", "0.05",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    evals = [r for r in records if "eval_mse" in r and r.get("note") != "final"]
    final = [r for r in records if r.get("note") == "final"][0]
    last = [r for r in evals if r["step"] == final["step"]]
    assert last, (evals, final)
    np.testing.assert_allclose(last[0]["eval_mse"], final["eval_mse"],
                               rtol=1e-4)


def test_cli_fused_eval_rejected_with_lm_tp():
    import pytest

    from lstm_tensorspark_tpu.cli import main

    with pytest.raises(SystemExit):
        main([
            "--dataset", "ptb_char", "--num-steps", "2", "--fused-eval",
            "--eval-every", "2", "--tensor-parallel", "2",
        ])
