"""Combined 3D parallelism (DP x TP x SP on one mesh): exact loss parity
with the single-device step over several steps."""

import jax
import numpy as np

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_mesh
from lstm_tensorspark_tpu.parallel.tensor_parallel import place_lm_params
from lstm_tensorspark_tpu.parallel.train_step import make_sharded_lm_train_step
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 11, 16, 8, 16


def test_dp_tp_sp_matches_single_device():
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)

    def loss_fn(p, b, r):
        return lm_loss(p, b, cfg)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    rngb = np.random.RandomState(0)
    batches = [
        {
            "inputs": rngb.randint(0, V, (B, T)).astype(np.int32),
            "targets": rngb.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(3)
    ]

    step0 = make_train_step(loss_fn, opt)
    s0 = init_train_state(params, opt, jax.random.PRNGKey(1))
    want = []
    for b in batches:
        s0, m = step0(s0, b)
        want.append(float(m["loss"]))

    mesh = make_mesh(dp=2, tp=2, sp=2)
    placed = place_lm_params(params, mesh)
    step3 = make_sharded_lm_train_step(cfg, opt, mesh, params,
                                       microbatches=2, donate=False)
    s3 = init_train_state(placed, opt, jax.random.PRNGKey(1))
    got = []
    for b in batches:
        s3, m = step3(s3, b)
        got.append(float(m["loss"]))

    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # params updated identically
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5
        ),
        jax.device_get(s0.params), jax.device_get(s3.params),
    )


def test_dp_tp_sp_matches_single_device_bf16_logits():
    """logits_dtype="bfloat16" under DP x TP x SP: the head matmul's
    partial products round to bf16 on each model shard BEFORE the GSPMD
    psum (vs add-then-round unsharded), so the law here is
    tolerance-close, not bit-equal — loss within bf16 rounding of the
    single-device bf16-logits run, and training stays finite and aligned
    over steps."""
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2,
                   logits_dtype="bfloat16")

    def loss_fn(p, b, r):
        return lm_loss(p, b, cfg)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(2), cfg)
    rngb = np.random.RandomState(1)
    batches = [
        {
            "inputs": rngb.randint(0, V, (B, T)).astype(np.int32),
            "targets": rngb.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(3)
    ]

    step0 = make_train_step(loss_fn, opt)
    s0 = init_train_state(params, opt, jax.random.PRNGKey(1))
    want = []
    for b in batches:
        s0, m = step0(s0, b)
        want.append(float(m["loss"]))

    mesh = make_mesh(dp=2, tp=2, sp=2)
    placed = place_lm_params(params, mesh)
    step3 = make_sharded_lm_train_step(cfg, opt, mesh, params,
                                       microbatches=2, donate=False)
    s3 = init_train_state(placed, opt, jax.random.PRNGKey(1))
    got = []
    for b in batches:
        s3, m = step3(s3, b)
        got.append(float(m["loss"]))

    assert np.isfinite(got).all()
    # bf16 rounding of sharded partials: ~3 decimal digits of agreement
    np.testing.assert_allclose(got, want, rtol=5e-3)
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-2, atol=2e-3
        ),
        jax.device_get(s0.params), jax.device_get(s3.params),
    )
