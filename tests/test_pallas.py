"""Fused Pallas LSTM kernel: interpret-mode parity on CPU (the kernel logic),
supported() gating, and the custom-VJP gradient path."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.ops import init_lstm_params, lstm_scan
from lstm_tensorspark_tpu.ops.pallas_lstm import pallas_lstm_scan, supported

B, T, D, H = 8, 10, 16, 128


def _setup():
    params = init_lstm_params(jax.random.PRNGKey(0), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    return params, xs


def test_supported_gating():
    assert not supported(B, H, platform="cpu")
    assert supported(8, 128, platform="tpu")
    assert not supported(7, 128, platform="tpu")  # sublane misalignment
    # lane misalignment is handled by internal padding now
    assert supported(8, 100, platform="tpu")
    assert supported(8, 650, platform="tpu")  # config 3, padded to 768


def test_interpret_forward_parity():
    params, xs = _setup()
    (hT, cT), ys = pallas_lstm_scan(params, xs, interpret=True)
    (hT2, cT2), ys2 = lstm_scan(params, xs)
    np.testing.assert_allclose(ys, ys2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT, hT2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cT, cT2, rtol=1e-5, atol=1e-5)


def test_interpret_with_carry():
    params, xs = _setup()
    h0 = jax.random.normal(jax.random.PRNGKey(2), (B, H))
    c0 = jax.random.normal(jax.random.PRNGKey(3), (B, H))
    (hT, _), ys = pallas_lstm_scan(params, xs, (h0, c0), interpret=True)
    (hT2, _), ys2 = lstm_scan(params, xs, (h0, c0))
    np.testing.assert_allclose(ys, ys2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT, hT2, rtol=1e-5, atol=1e-5)


def test_grad_parity():
    """Custom VJP recomputes through the reference scan — grads must match."""
    params, xs = _setup()

    def loss_p(p):
        return jnp.mean(pallas_lstm_scan(p, xs, interpret=True)[1] ** 2)

    def loss_r(p):
        return jnp.mean(lstm_scan(p, xs)[1] ** 2)

    g1 = jax.grad(loss_p)(params)
    g2 = jax.grad(loss_r)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_stacked_scan_fallback_on_cpu():
    """use_pallas on unsupported platform silently falls back to lax.scan."""
    from lstm_tensorspark_tpu.ops import stacked_lstm_scan

    params, xs = _setup()
    finals, ys = stacked_lstm_scan([params], xs, use_pallas=True)
    _, ys2 = lstm_scan(params, xs)
    np.testing.assert_allclose(ys, ys2, rtol=1e-6)


def test_supported_vmem_bound():
    """H=1024 f32 (resident U would be 16 MiB) now plans onto the TILED
    kernel instead of falling back; gigantic B·H still gates to False."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import _plan_fwd

    assert supported(8, 1024, platform="tpu")  # tiled (config 5)
    assert _plan_fwd(8, 1024, 4, save_residuals=False)[0] == "tiled"
    assert _plan_fwd(8, 512, 4, save_residuals=False)[0] == "resident"
    assert supported(8, 512, platform="tpu")
    # a shape whose per-step blocks alone blow VMEM must still gate off
    assert not supported(4096, 4096, platform="tpu")


def test_grad_parity_with_remat_chunk():
    """remat_chunk threads through the custom VJP's recompute unchanged."""
    params, xs = _setup()

    def loss_p(p):
        return jnp.mean(
            pallas_lstm_scan(p, xs, remat_chunk=5, interpret=True)[1] ** 2
        )

    def loss_r(p):
        return jnp.mean(lstm_scan(p, xs)[1] ** 2)

    g1 = jax.grad(loss_p)(params)
    g2 = jax.grad(loss_r)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_fused_backward_with_carry_cotangents():
    """Fused bwd must handle gradients flowing through (hT, cT) AND ys,
    with a nonzero initial carry."""
    params, xs = _setup()
    h0 = jax.random.normal(jax.random.PRNGKey(4), (B, H))
    c0 = jax.random.normal(jax.random.PRNGKey(5), (B, H))

    def loss(scan_fn):
        def f(p, h, c):
            (hT, cT), ys = scan_fn(p, xs, (h, c))
            return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)
        return f

    import functools
    g1 = jax.grad(loss(functools.partial(pallas_lstm_scan, interpret=True)),
                  argnums=(0, 1, 2))(params, h0, c0)
    g2 = jax.grad(loss(lstm_scan), argnums=(0, 1, 2))(params, h0, c0)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_fused_backward_xs_gradient():
    """Gradients wrt the inputs (needed by stacked layers) match the scan."""
    params, _ = _setup()
    xs = jax.random.normal(jax.random.PRNGKey(6), (B, T, D))

    def lp(x):
        return jnp.mean(pallas_lstm_scan(params, x, interpret=True)[1] ** 2)

    def lr(x):
        return jnp.mean(lstm_scan(params, x)[1] ** 2)

    np.testing.assert_allclose(
        jax.grad(lp)(xs), jax.grad(lr)(xs), rtol=1e-4, atol=1e-6
    )


def test_fused_backward_bf16_close_to_f32():
    """bf16 compute dtype: fused bwd grads stay within bf16 tolerance of the
    f32 scan reference."""
    params, xs = _setup()

    def lp(p):
        return jnp.mean(
            pallas_lstm_scan(p, xs, compute_dtype=jnp.bfloat16,
                             interpret=True)[1] ** 2
        )

    def lr(p):
        return jnp.mean(lstm_scan(p, xs)[1] ** 2)

    g1 = jax.grad(lp)(params)
    g2 = jax.grad(lr)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.1, atol=0.02,
        ),
        g1, g2,
    )


def test_tiled_forward_and_grad_parity_h1024():
    """H=1024 f32 selects the TILED kernels (U streamed in row-tiles, dU
    computed outside); forward and grads must match the scan reference."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import _plan_bwd, _plan_fwd

    assert _plan_fwd(8, 1024, 4, save_residuals=True)[0] == "tiled"
    assert _plan_bwd(8, 1024, 4)[0] == "tiled"
    params = init_lstm_params(jax.random.PRNGKey(7), 32, 1024)
    xs = jax.random.normal(jax.random.PRNGKey(8), (8, 4, 32))
    (hT, cT), ys = pallas_lstm_scan(params, xs, interpret=True)
    (hT2, cT2), ys2 = lstm_scan(params, xs)
    np.testing.assert_allclose(ys, ys2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT, hT2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cT, cT2, rtol=1e-5, atol=1e-5)

    def lp(p, x):
        return jnp.mean(pallas_lstm_scan(p, x, interpret=True)[1] ** 2)

    def lr(p, x):
        return jnp.mean(lstm_scan(p, x)[1] ** 2)

    g1 = jax.grad(lp, argnums=(0, 1))(params, xs)
    g2 = jax.grad(lr, argnums=(0, 1))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        g1, g2,
    )


def test_padded_h650_parity():
    """H=650 (config 3) pads to 768 internally; forward AND grads must be
    exact vs the unpadded scan (padding analysis: dz_pad = 0 identically)."""
    params = init_lstm_params(jax.random.PRNGKey(9), 48, 650)
    xs = jax.random.normal(jax.random.PRNGKey(10), (8, 6, 48))
    h0 = jax.random.normal(jax.random.PRNGKey(11), (8, 650))
    c0 = jax.random.normal(jax.random.PRNGKey(12), (8, 650))
    (hT, cT), ys = pallas_lstm_scan(params, xs, (h0, c0), interpret=True)
    (hT2, cT2), ys2 = lstm_scan(params, xs, (h0, c0))
    assert ys.shape == ys2.shape == (8, 6, 650)
    np.testing.assert_allclose(ys, ys2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT, hT2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cT, cT2, rtol=1e-5, atol=1e-5)

    def lp(p, h, c):
        (hT, cT), ys = pallas_lstm_scan(p, xs, (h, c), interpret=True)
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    def lr(p, h, c):
        (hT, cT), ys = lstm_scan(p, xs, (h, c))
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    g1 = jax.grad(lp, argnums=(0, 1, 2))(params, h0, c0)
    g2 = jax.grad(lr, argnums=(0, 1, 2))(params, h0, c0)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        g1, g2,
    )


def test_residual_hbm_heuristic(monkeypatch):
    """Residual bytes above the HBM budget select the recompute backward
    (no z residuals saved) — ADVICE.md's memory-regression guard."""
    import lstm_tensorspark_tpu.ops.pallas_lstm as pallas_mod

    params, xs = _setup()
    g_fused = jax.grad(
        lambda p: jnp.mean(pallas_lstm_scan(p, xs, interpret=True)[1] ** 2)
    )(params)
    monkeypatch.setattr(pallas_mod, "_RESIDUAL_HBM_BUDGET", 1)  # force off
    g_recompute = jax.grad(
        lambda p: jnp.mean(pallas_lstm_scan(p, xs, interpret=True)[1] ** 2)
    )(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g_fused, g_recompute,
    )


# ---------------------------------------------------------------------------
# masked / reversed scans (round 3: configs 2 & 4 fused-path coverage)
# ---------------------------------------------------------------------------


def _lengths_mask(key, b, t):
    lengths = jax.random.randint(key, (b,), 1, t + 1)
    return jnp.arange(t)[None, :] < lengths[:, None]


def test_masked_forward_parity():
    params, xs = _setup()
    mask = _lengths_mask(jax.random.PRNGKey(20), B, T)
    (hT, cT), ys = pallas_lstm_scan(params, xs, mask=mask, interpret=True)
    (hT2, cT2), ys2 = lstm_scan(params, xs, mask=mask)
    np.testing.assert_allclose(ys, ys2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT, hT2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cT, cT2, rtol=1e-5, atol=1e-5)


def test_reverse_forward_parity():
    params, xs = _setup()
    h0 = jax.random.normal(jax.random.PRNGKey(21), (B, H))
    c0 = jax.random.normal(jax.random.PRNGKey(22), (B, H))
    (hT, cT), ys = pallas_lstm_scan(
        params, xs, (h0, c0), reverse=True, interpret=True
    )
    (hT2, cT2), ys2 = lstm_scan(params, xs, (h0, c0), reverse=True)
    np.testing.assert_allclose(ys, ys2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT, hT2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cT, cT2, rtol=1e-5, atol=1e-5)


def test_masked_reverse_parity():
    """The bi-LSTM's backward direction: reversed scan over a right-padded
    batch with a carry-freeze mask. Forward AND grads must match."""
    params, xs = _setup()
    mask = _lengths_mask(jax.random.PRNGKey(23), B, T)

    def lp(p, x):
        (hT, cT), ys = pallas_lstm_scan(
            p, x, mask=mask, reverse=True, interpret=True
        )
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    def lr(p, x):
        (hT, cT), ys = lstm_scan(p, x, mask=mask, reverse=True)
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    np.testing.assert_allclose(lp(params, xs), lr(params, xs),
                               rtol=1e-5, atol=1e-6)
    g1 = jax.grad(lp, argnums=(0, 1))(params, xs)
    g2 = jax.grad(lr, argnums=(0, 1))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_masked_grad_parity_fused_bwd():
    """Masked FUSED backward (not the recompute fallback): the masked
    cotangent algebra inside _lstm_bwd_kernel must match lstm_scan grads."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import _plan_bwd

    assert _plan_bwd(B, H, 4, True) is not None  # fused bwd is the live path
    params, xs = _setup()
    mask = _lengths_mask(jax.random.PRNGKey(24), B, T)
    h0 = jax.random.normal(jax.random.PRNGKey(25), (B, H))
    c0 = jax.random.normal(jax.random.PRNGKey(26), (B, H))

    def lp(p, x, h, c):
        (hT, cT), ys = pallas_lstm_scan(p, x, (h, c), mask=mask,
                                        interpret=True)
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    def lr(p, x, h, c):
        (hT, cT), ys = lstm_scan(p, x, (h, c), mask=mask)
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    g1 = jax.grad(lp, argnums=(0, 1, 2, 3))(params, xs, h0, c0)
    g2 = jax.grad(lr, argnums=(0, 1, 2, 3))(params, xs, h0, c0)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_masked_tiled_parity():
    """Masked TILED kernels (H=1024 → U streamed): forward + grads."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import _plan_bwd, _plan_fwd

    assert _plan_fwd(8, 1024, 4, save_residuals=True, has_mask=True)[0] == "tiled"
    assert _plan_bwd(8, 1024, 4, True)[0] == "tiled"
    params = init_lstm_params(jax.random.PRNGKey(27), 32, 1024)
    xs = jax.random.normal(jax.random.PRNGKey(28), (8, 4, 32))
    mask = _lengths_mask(jax.random.PRNGKey(29), 8, 4)

    (hT, cT), ys = pallas_lstm_scan(params, xs, mask=mask, interpret=True)
    (hT2, cT2), ys2 = lstm_scan(params, xs, mask=mask)
    np.testing.assert_allclose(ys, ys2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT, hT2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(cT, cT2, rtol=1e-5, atol=1e-5)

    def lp(p, x):
        return jnp.mean(pallas_lstm_scan(p, x, mask=mask, interpret=True)[1] ** 2)

    def lr(p, x):
        return jnp.mean(lstm_scan(p, x, mask=mask)[1] ** 2)

    g1 = jax.grad(lp, argnums=(0, 1))(params, xs)
    g2 = jax.grad(lr, argnums=(0, 1))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        g1, g2,
    )


def test_masked_recompute_bwd_parity(monkeypatch):
    """Masked scan with the recompute backward (residual budget forced to 0):
    the fallback must thread the mask through lstm_scan."""
    import lstm_tensorspark_tpu.ops.pallas_lstm as pallas_mod

    monkeypatch.setattr(pallas_mod, "_RESIDUAL_HBM_BUDGET", 1)
    params, xs = _setup()
    mask = _lengths_mask(jax.random.PRNGKey(30), B, T)

    def lp(p):
        return jnp.mean(
            pallas_lstm_scan(p, xs, mask=mask, interpret=True)[1] ** 2
        )

    def lr(p):
        return jnp.mean(lstm_scan(p, xs, mask=mask)[1] ** 2)

    g1 = jax.grad(lp)(params)
    g2 = jax.grad(lr)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_masked_padded_h650_parity():
    """Mask + lane padding together (config-3-like H=650 → padded 768)."""
    params = init_lstm_params(jax.random.PRNGKey(31), 48, 650)
    xs = jax.random.normal(jax.random.PRNGKey(32), (8, 6, 48))
    mask = _lengths_mask(jax.random.PRNGKey(33), 8, 6)
    (hT, cT), ys = pallas_lstm_scan(params, xs, mask=mask, interpret=True)
    (hT2, cT2), ys2 = lstm_scan(params, xs, mask=mask)
    np.testing.assert_allclose(ys, ys2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(hT, hT2, rtol=1e-5, atol=1e-5)

    def lp(p):
        return jnp.mean(pallas_lstm_scan(p, xs, mask=mask, interpret=True)[1] ** 2)

    def lr(p):
        return jnp.mean(lstm_scan(p, xs, mask=mask)[1] ** 2)

    g1 = jax.grad(lp)(params)
    g2 = jax.grad(lr)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


# ---------------------------------------------------------------------------
# fully-fused residentx strategy (in-kernel xproj + recompute-z backward)
# ---------------------------------------------------------------------------


def test_residentx_is_planned_for_small_shapes():
    from lstm_tensorspark_tpu.ops.pallas_lstm import _plan_bwd, _plan_fwd

    # config-1/2/4 shape class: both directions of the pair fit
    assert _plan_fwd(64, 128, 2, save_residuals=True, Dp=128)[0] == "residentx"
    assert _plan_bwd(64, 128, 2, False, 128)[0] == "residentx"
    assert _plan_fwd(64, 256, 2, save_residuals=True, Dp=512)[0] == "residentx"
    assert _plan_bwd(64, 256, 2, False, 512)[0] == "residentx"
    # H=1024: U+U^T resident cannot fit — falls to the legacy strategies
    assert _plan_bwd(8, 1024, 4, False, 128)[0] == "tiled"
    # no Dp (hoisted-xproj callers): residentx is never offered
    assert _plan_fwd(64, 128, 2, save_residuals=True)[0] == "resident"


def test_residentx_grads_with_mask_carry_and_padded_d(monkeypatch):
    """The fully-fused pair at an off-lane input width (D=50 → padded 128):
    forward + grads (params, xs, carry) must match lstm_scan, mask on.
    (_FUSEDX_MIN_T forced to 0 so the short test sequence takes the path.)"""
    import lstm_tensorspark_tpu.ops.pallas_lstm as pallas_mod
    from lstm_tensorspark_tpu.ops.pallas_lstm import _plan_bwd

    monkeypatch.setattr(pallas_mod, "_FUSEDX_MIN_T", 0)
    D_odd = 50
    assert _plan_bwd(B, H, 4, True, 128)[0] == "residentx"
    params = init_lstm_params(jax.random.PRNGKey(40), D_odd, H)
    xs = jax.random.normal(jax.random.PRNGKey(41), (B, T, D_odd))
    mask = _lengths_mask(jax.random.PRNGKey(42), B, T)
    h0 = jax.random.normal(jax.random.PRNGKey(43), (B, H))
    c0 = jax.random.normal(jax.random.PRNGKey(44), (B, H))

    def lp(p, x, h, c):
        (hT, cT), ys = pallas_lstm_scan(p, x, (h, c), mask=mask,
                                        interpret=True)
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    def lr(p, x, h, c):
        (hT, cT), ys = lstm_scan(p, x, (h, c), mask=mask)
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    np.testing.assert_allclose(lp(params, xs, h0, c0), lr(params, xs, h0, c0),
                               rtol=1e-5, atol=1e-6)
    g1 = jax.grad(lp, argnums=(0, 1, 2, 3))(params, xs, h0, c0)
    g2 = jax.grad(lr, argnums=(0, 1, 2, 3))(params, xs, h0, c0)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_legacy_resident_path_still_works(monkeypatch):
    """Force the hoisted-xproj resident pair (residentx priced out) — the
    legacy path must stay healthy for shapes where W cannot be resident."""
    import lstm_tensorspark_tpu.ops.pallas_lstm as pallas_mod

    monkeypatch.setattr(pallas_mod, "_residentx_fwd_vmem",
                        lambda *a, **k: 10**12)
    monkeypatch.setattr(pallas_mod, "_residentx_bwd_vmem",
                        lambda *a, **k: 10**12)
    assert pallas_mod._plan_fwd(B, H, 4, save_residuals=True,
                                Dp=128)[0] == "resident"
    params, xs = _setup()
    mask = _lengths_mask(jax.random.PRNGKey(45), B, T)

    def lp(p):
        return jnp.mean(
            pallas_lstm_scan(p, xs, mask=mask, interpret=True)[1] ** 2
        )

    def lr(p):
        return jnp.mean(lstm_scan(p, xs, mask=mask)[1] ** 2)

    g1 = jax.grad(lp)(params)
    g2 = jax.grad(lr)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_du_hoist_loosens_resident_bwd_plan():
    """dU is contracted outside every sequential kernel (from the streamed
    dz), so the backward cost model carries no [H,4H] f32 accumulator and
    no h_prev input stream. The config-4 encoder class (B=64, H=256 bf16,
    no mask, hoisted xproj) fits the RESIDENT backward again — under the
    old accounting it priced out to tiled. Big-H shapes still tile."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import _plan_bwd

    assert _plan_bwd(64, 256, 2, False, None)[0] == "resident"
    # r4 chunk-flexible planning + bf16 streams: big-H bf16 shapes now fit
    # the U-resident backward at a SMALLER time chunk instead of paying
    # tiled's per-timestep U^T re-stream
    assert _plan_bwd(64, 768, 2, False, None) == ("resident", 2)
    assert _plan_bwd(32, 1024, 2, False, None) == ("resident", 2)
    # f32 streams keep big-H on the tiled strategy (U alone ~16.8 MB f32
    # at H=1024 exceeds the VMEM budget)
    assert _plan_bwd(64, 768, 4, False, None)[0] == "tiled"
    assert _plan_bwd(32, 1024, 4, False, None)[0] == "tiled"


def test_bf16_stream_residuals_grad_tolerance(monkeypatch):
    """r4 bandwidth fix: under bf16 compute the z/dz/xproj HBM streams
    are STORED bf16 (gate math stays f32 in-kernel). Gradients through
    the fused backward must stay within bf16-scale tolerance of the f32
    reference, and LSTM_TSP_RESIDUAL_F32=1 must restore the old f32
    streams exactly."""
    import functools

    import lstm_tensorspark_tpu.ops.pallas_lstm as pallas_mod

    params, xs = _setup()

    def loss(run):
        def f(p, x):
            (hT, cT), ys = run(p, x)
            return jnp.mean(ys ** 2) + jnp.mean(hT) + jnp.mean(cT ** 2)
        return f

    run_p = functools.partial(pallas_lstm_scan, compute_dtype=jnp.bfloat16,
                              interpret=True)
    run_r = functools.partial(lstm_scan, compute_dtype=jnp.bfloat16)
    g_bf16 = jax.grad(loss(run_p), argnums=(0, 1))(params, xs)
    g_ref = jax.grad(loss(run_r), argnums=(0, 1))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-2, atol=5e-3),
        g_bf16, g_ref,
    )

    # kill-switch: f32 streams under bf16 compute (the A/B lever)
    monkeypatch.setenv("LSTM_TSP_RESIDUAL_F32", "1")
    assert pallas_mod._rbytes(2) == 4
    g_f32s = jax.grad(loss(run_p), argnums=(0, 1))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-2, atol=5e-3),
        g_f32s, g_ref,
    )


def test_bf16_tiled_bigh_grad_parity():
    """ADVICE r4: the bf16 stored-z rounding (forward computes gates from
    f32 z, backward recomputes them from the bf16-rounded STORED z) must
    stay within bf16 tolerance on the TILED path too, not just
    resident/residentx — H=1536 bf16 is the smallest shape that spills
    past every resident chunk and plans tiled for both passes."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import (
        _plan_bwd, _plan_fwd, chosen_bwd_strategy,
    )

    Bt, Tt, Dt, Ht = 8, 4, 16, 1536
    assert _plan_fwd(Bt, Ht, 2, save_residuals=True)[0] == "tiled"
    assert _plan_bwd(Bt, Ht, 2, False, None)[0] == "tiled"
    assert chosen_bwd_strategy(Bt, Tt, Ht, 2) == "tiled"

    params = init_lstm_params(jax.random.PRNGKey(11), Dt, Ht)
    xs = jax.random.normal(jax.random.PRNGKey(12), (Bt, Tt, Dt))

    def lp(p):
        return jnp.mean(pallas_lstm_scan(
            p, xs, compute_dtype=jnp.bfloat16, interpret=True)[1] ** 2)

    def lr(p):
        return jnp.mean(lstm_scan(p, xs, compute_dtype=jnp.bfloat16)[1] ** 2)

    np.testing.assert_allclose(
        jax.jit(lp)(params), jax.jit(lr)(params), rtol=2e-2, atol=2e-3)
    g1 = jax.grad(lp)(params)
    g2 = jax.grad(lr)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=8e-2, atol=8e-3),
        g1, g2,
    )


def test_f32_compute_keeps_f32_streams():
    """f32 compute must keep bit-exact f32 residual streams — the exact
    interpret-mode parities above depend on it."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import (
        _rbytes, _residual_dtype,
    )

    assert _residual_dtype(jnp.float32) == jnp.float32
    assert _rbytes(4) == 4
    assert _residual_dtype(jnp.bfloat16) == jnp.bfloat16
    assert _rbytes(2) == 2


def test_chunk2_resident_bf16_bigh_parity():
    """The r4 plan flip: H=650-class bf16 shapes run the U-RESIDENT pair
    at time chunk 2 (instead of tiled's per-timestep U re-stream). Pin
    the plan and check fwd+grad parity through the chunk-2 kernels in
    interpret mode at bf16 tolerance."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import _plan_bwd, _plan_fwd

    Bc, Tc, Dc, Hc = 64, 6, 16, 650  # padded H = 768
    assert _plan_fwd(Bc, 768, 2, save_residuals=True) == ("resident", 2)
    assert _plan_bwd(Bc, 768, 2, False, None) == ("resident", 2)

    params = init_lstm_params(jax.random.PRNGKey(7), Dc, Hc)
    xs = jax.random.normal(jax.random.PRNGKey(8), (Bc, Tc, Dc))
    (hT, cT), ys = pallas_lstm_scan(params, xs, compute_dtype=jnp.bfloat16,
                                    interpret=True)
    (hT2, cT2), ys2 = lstm_scan(params, xs, compute_dtype=jnp.bfloat16)
    np.testing.assert_allclose(ys, ys2, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(hT, hT2, rtol=2e-2, atol=2e-2)

    def lp(p):
        return jnp.mean(pallas_lstm_scan(
            p, xs, compute_dtype=jnp.bfloat16, interpret=True)[1] ** 2)

    def lr(p):
        return jnp.mean(lstm_scan(p, xs, compute_dtype=jnp.bfloat16)[1] ** 2)

    g1 = jax.grad(lp)(params)
    g2 = jax.grad(lr)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=8e-2, atol=8e-3),
        g1, g2,
    )
