"""Tensor-parallel (GSPMD-sharded) LM training: loss parity with the
single-device step under pure TP and combined DP x TP meshes, and sharded
parameter placement."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_mesh
from lstm_tensorspark_tpu.parallel.tensor_parallel import (
    make_tp_train_step,
    place_lm_params,
)
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 11, 16, 8, 12


def _setup(num_layers=2):
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=num_layers)

    def loss_fn(params, batch, rng):
        return lm_loss(params, batch, cfg)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batches = [
        {
            "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
            "targets": rng.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(4)
    ]
    return cfg, loss_fn, opt, params, batches


def _single_losses(loss_fn, opt, params, batches):
    step = make_train_step(loss_fn, opt)
    s = init_train_state(params, opt, jax.random.PRNGKey(1))
    out = []
    for b in batches:
        s, m = step(s, b)
        out.append(float(m["loss"]))
    return out, s


def _tp_losses(mesh, loss_fn, opt, params, batches):
    placed = place_lm_params(params, mesh)
    step = make_tp_train_step(loss_fn, opt, mesh, params, donate=False)
    s = init_train_state(placed, opt, jax.random.PRNGKey(1))
    out = []
    for b in batches:
        s, m = step(s, b)
        out.append(float(m["loss"]))
    return out, s


def test_params_actually_sharded():
    cfg, loss_fn, opt, params, batches = _setup()
    mesh = make_mesh(dp=1, tp=8, sp=1)
    placed = place_lm_params(params, mesh)
    W = placed["layers"][0].W_i  # [D, H] column-sharded into H/8
    shard_shapes = {s.data.shape for s in W.addressable_shards}
    assert shard_shapes == {(H, H // 8)} or shard_shapes == {(W.shape[0], H // 8)}
    emb = placed["embedding"]
    assert all(s.data.shape == emb.shape for s in emb.addressable_shards)


def test_tp_matches_single_device():
    cfg, loss_fn, opt, params, batches = _setup()
    want, s_ref = _single_losses(loss_fn, opt, params, batches)
    mesh = make_mesh(dp=1, tp=8, sp=1)
    got, s_tp = _tp_losses(mesh, loss_fn, opt, params, batches)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        jax.device_get(s_ref.params), jax.device_get(s_tp.params),
    )


def test_dp_tp_combined_matches_single_device():
    cfg, loss_fn, opt, params, batches = _setup()
    want, _ = _single_losses(loss_fn, opt, params, batches)
    mesh = make_mesh(dp=2, tp=4, sp=1)
    got, _ = _tp_losses(mesh, loss_fn, opt, params, batches)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
