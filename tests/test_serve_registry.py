"""Model registry (serve/registry.py) + supervise publication: verified
publish/load roundtrips, version immutability, peer adoption via rescan,
quarantine of truncated AND bit-flipped artifacts (a corrupt artifact is
never served), config-fingerprint version-skew material, and the
``supervise --registry-dir`` best-checkpoint promotion hook."""

import json
import os

import jax
import pytest
from flax import serialization

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.serve import (
    ModelRegistry,
    RegistryError,
    config_fingerprint,
)
from lstm_tensorspark_tpu.supervise import _publish_best
from lstm_tensorspark_tpu.train.checkpoint import atomic_write

_CFG = LMConfig(vocab_size=29, hidden_size=16, num_layers=1)


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(3), _CFG)


# ---- publish / load ---------------------------------------------------


def test_publish_load_roundtrip(tmp_path, params):
    """Params published as bytes come back decoded against the engine's
    template, with the metadata record intact."""
    reg = ModelRegistry(str(tmp_path))
    meta = reg.publish("m", serialization.to_bytes(params),
                       config_hash=config_fingerprint(_CFG),
                       parent="best.msgpack @ step 7")
    assert meta["version"] == 1 and meta["kind"] == "params"
    got_meta, got = reg.load_params("m", params)
    assert got_meta["parent"] == "best.msgpack @ step 7"
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(got)
    assert all((a == b).all() for a, b in zip(flat_a, flat_b))


def test_auto_versioning_and_immutability(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    assert reg.publish("m", b"one")["version"] == 1
    assert reg.publish("m", b"two")["version"] == 2
    with pytest.raises(ValueError, match="immutable"):
        reg.publish("m", b"redo", version=2)
    assert reg.latest("m")["version"] == 2
    _, payload = reg.load_bytes("m", 1)
    assert payload == b"one"


def test_bad_ids_and_unknown_lookups(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    for bad in ("", "a/b", "x__v1"):
        with pytest.raises(ValueError):
            reg.publish(bad, b"p")
    with pytest.raises(RegistryError, match="unknown model"):
        reg.meta("ghost")
    reg.publish("m", b"p")
    with pytest.raises(RegistryError, match="no version 9"):
        reg.load_bytes("m", 9)


def test_peer_adoption_via_scan(tmp_path):
    """A second registry instance over the same directory (the serving
    fleet next to the publishing supervisor) indexes everything the peer
    published — the filesystem is the only coordination."""
    a = ModelRegistry(str(tmp_path))
    a.publish("m", b"v1-bytes")
    b = ModelRegistry(str(tmp_path))
    assert b.models() == {"m": [1]}
    a.publish("m", b"v2-bytes")
    assert b.models() == {"m": [1]}  # stale until rescan, by design
    b.scan()
    assert b.models() == {"m": [1, 2]}


def test_orphan_payload_adopted_with_reconstructed_meta(tmp_path):
    """publish crashing between payload and metadata record leaves a
    verified payload with no .json — the next scan adopts it instead of
    stranding it."""
    reg = ModelRegistry(str(tmp_path))
    reg.publish("m", b"payload")
    os.remove(tmp_path / "m__v000001.json")
    reg.scan()
    meta = reg.meta("m", 1)
    assert meta["kind"] == "params" and meta["payload_bytes"] == 7


# ---- quarantine -------------------------------------------------------


def test_truncated_artifact_quarantined_on_scan(tmp_path):
    reg = ModelRegistry(str(tmp_path))
    reg.publish("m", b"x" * 64)
    path = tmp_path / "m__v000001.msgpack"
    path.write_bytes(b"x" * 10)  # truncation: sha sidecar now mismatches
    fresh = ModelRegistry(str(tmp_path))
    assert fresh.models() == {}
    assert fresh.quarantined == 1
    assert (tmp_path / "m__v000001.msgpack.quarantined").exists()
    with pytest.raises(RegistryError):
        fresh.load_bytes("m", 1)


def test_bit_flip_after_index_quarantined_at_load(tmp_path):
    """Corruption landing AFTER the indexing scan is caught by the
    per-load verification: the artifact is quarantined, drops out of the
    manifest, and the load raises — it is never served."""
    reg = ModelRegistry(str(tmp_path))
    reg.publish("m", b"A" * 64)
    path = tmp_path / "m__v000001.msgpack"
    blob = bytearray(path.read_bytes())
    blob[13] ^= 0x40
    path.write_bytes(bytes(blob))
    with pytest.raises(RegistryError, match="quarantined"):
        reg.load_bytes("m")
    assert reg.models() == {}
    assert reg.quarantined == 1
    assert (tmp_path / "m__v000001.msgpack.quarantined").exists()
    # the good sibling-model path still works after the quarantine
    reg.publish("other", b"fine")
    assert reg.load_bytes("other")[1] == b"fine"


def test_config_fingerprint_stability():
    assert config_fingerprint(_CFG) == config_fingerprint(
        LMConfig(vocab_size=29, hidden_size=16, num_layers=1))
    assert config_fingerprint(_CFG) != config_fingerprint(
        LMConfig(vocab_size=29, hidden_size=32, num_layers=1))


# ---- supervise publication -------------------------------------------


def _write_best(ckpt_dir, params, step=5, value=1.25):
    """The single-process best artifact exactly as train/checkpoint.py
    writes it: msgpack {step, value, state=to_bytes(state)} + sidecar +
    best.json."""
    os.makedirs(ckpt_dir, exist_ok=True)
    payload = serialization.msgpack_serialize({
        "step": step, "value": value,
        "state": serialization.to_bytes({"params": params}),
    })
    atomic_write(os.path.join(ckpt_dir, "best.msgpack"), payload,
                 checksum=True)
    with open(os.path.join(ckpt_dir, "best.json"), "w") as f:
        json.dump({"step": step, "value": value}, f)


def test_supervise_publishes_best_state(tmp_path, params):
    ckpt = tmp_path / "ckpt"
    regdir = tmp_path / "registry"
    _write_best(str(ckpt), params, step=5)
    meta = _publish_best(str(ckpt), str(regdir), "default")
    assert meta["version"] == 5 and meta["kind"] == "best_state"
    assert meta["parent"] == "best.msgpack @ step 5"
    # re-publication of the same step is a no-op (versions are immutable)
    assert _publish_best(str(ckpt), str(regdir), "default") is None
    # a NEW best step publishes the next version
    _write_best(str(ckpt), params, step=9)
    assert _publish_best(str(ckpt), str(regdir), "default")["version"] == 9
    # the serve side decodes best_state against its params template
    reg = ModelRegistry(str(regdir))
    got_meta, got = reg.load_params("default", params, 5)
    assert got_meta["kind"] == "best_state"
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(got)
    assert all((a == b).all() for a, b in zip(flat_a, flat_b))


def test_supervise_skips_missing_or_corrupt_best(tmp_path, params):
    assert _publish_best(str(tmp_path / "none"), str(tmp_path / "r"),
                         "m") is None
    ckpt = tmp_path / "ckpt"
    _write_best(str(ckpt), params, step=3)
    best = ckpt / "best.msgpack"
    best.write_bytes(best.read_bytes()[:-7])  # truncated: fails sha
    assert _publish_best(str(ckpt), str(tmp_path / "r"), "m") is None
    assert not os.path.isdir(tmp_path / "r") or ModelRegistry(
        str(tmp_path / "r")).models() == {}
