"""Randomized property sweep over the scan surface: for many seeded random
(shape, mask, reverse, remat, dtype, unroll) combinations, `lstm_scan` and
its variants must agree with the step-at-a-time oracle built from
`lstm_step_unfused` — value AND gradient. Complements the targeted cases in
tests/test_scan_ops.py-style files with breadth: the combinations are drawn
jointly, so interaction bugs (e.g. mask x reverse x remat) get coverage the
hand-picked cases may miss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lstm_tensorspark_tpu.ops import (
    init_lstm_params,
    lstm_scan,
    lstm_step_unfused,
    stacked_lstm_scan,
)


def _oracle(params, xs, mask=None, reverse=False):
    """Step-at-a-time reference with explicit python control flow."""
    B, T, D = xs.shape
    H = params.b_i.shape[0]
    h = jnp.zeros((B, H), xs.dtype)
    c = jnp.zeros((B, H), xs.dtype)
    order = range(T - 1, -1, -1) if reverse else range(T)
    outs = [None] * T
    for t in order:
        (h2, c2), _ = lstm_step_unfused(params, (h, c), xs[:, t])
        if mask is not None:
            m = mask[:, t][:, None].astype(xs.dtype)
            h = m * h2 + (1 - m) * h
            c = m * c2 + (1 - m) * c
        else:
            h, c = h2, c2
        outs[t] = h
    return jnp.stack(outs, axis=1), (h, c)


CASES = list(range(12))


@pytest.mark.parametrize("case", CASES)
def test_scan_matches_oracle_random_config(case):
    rng = np.random.RandomState(1000 + case)
    B = int(rng.choice([1, 2, 4, 8]))
    T = int(rng.choice([1, 2, 5, 9, 16]))
    D = int(rng.choice([3, 8, 16]))
    H = int(rng.choice([4, 8, 16]))
    reverse = bool(rng.rand() < 0.5)
    use_mask = bool(rng.rand() < 0.5)
    remat = int(rng.choice([0, 2, 4]))
    unroll = int(rng.choice([1, 2]))
    remat_chunk = remat if (remat and T % remat == 0) else None

    params = init_lstm_params(jax.random.PRNGKey(case), D, H)
    xs = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    mask = None
    if use_mask:
        # random valid lengths -> standard left-aligned mask
        lens = rng.randint(1, T + 1, size=B)
        mask = jnp.asarray(
            (np.arange(T)[None, :] < lens[:, None]), jnp.float32
        )

    want_ys, (want_h, want_c) = _oracle(params, xs, mask=mask, reverse=reverse)

    (h, c), ys = lstm_scan(
        params, xs, mask=mask, reverse=reverse,
        remat_chunk=remat_chunk, unroll=unroll,
    )
    np.testing.assert_allclose(np.asarray(ys), np.asarray(want_ys),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(h), np.asarray(want_h),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(c), np.asarray(want_c),
                               rtol=2e-5, atol=2e-5)

    # gradients agree with the oracle's
    def loss_scan(p):
        (h, _), ys = lstm_scan(p, xs, mask=mask, reverse=reverse,
                               remat_chunk=remat_chunk, unroll=unroll)
        return jnp.sum(ys ** 2) + jnp.sum(h)

    def loss_oracle(p):
        ys, (h, _) = _oracle(p, xs, mask=mask, reverse=reverse)
        return jnp.sum(ys ** 2) + jnp.sum(h)

    g1 = jax.grad(loss_scan)(params)
    g2 = jax.grad(loss_oracle)(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("case", CASES[:6])
def test_pallas_interpret_matches_plain_random_config(case):
    """The fused kernel's ALGORITHM (interpret-mode Pallas on CPU — the
    real kernel cannot run here; `auto_lstm_scan(use_pallas=True)` would
    silently fall back to `lstm_scan` and compare it with itself) must
    match `lstm_scan` for the same random mask/reverse configuration."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import pallas_lstm_scan

    rng = np.random.RandomState(2000 + case)
    B = int(rng.choice([8, 16]))  # kernel eligibility needs B % 8 == 0
    T = int(rng.choice([4, 8, 12]))
    D = int(rng.choice([8, 16]))
    H = int(rng.choice([8, 16]))
    reverse = bool(rng.rand() < 0.5)
    use_mask = bool(rng.rand() < 0.5)

    params = init_lstm_params(jax.random.PRNGKey(case), D, H)
    xs = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    mask = None
    if use_mask:
        lens = rng.randint(1, T + 1, size=B)
        mask = jnp.asarray(
            (np.arange(T)[None, :] < lens[:, None]), jnp.float32
        )

    (h1, c1), ys1 = lstm_scan(params, xs, mask=mask, reverse=reverse)
    (h2, c2), ys2 = pallas_lstm_scan(params, xs, mask=mask, reverse=reverse,
                                     interpret=True)
    np.testing.assert_allclose(np.asarray(ys1), np.asarray(ys2),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c2),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("case", CASES[:8])
def test_assoc_bptt_matches_sequential_random_config(case):
    """bptt="assoc" (ops/parallel_scan.py) vs the sequential VJP on
    jointly-drawn random (T, H, layers, mask pattern, dtype) configs —
    value AND gradient, fp32 and bf16-params/fp32-grads. The joint draw
    covers interaction surfaces (mask x layers x tile split x dtype)
    the targeted matrix in tests/test_parallel_scan.py fixes one at a
    time; tolerances are the fp64-validated ones from that file."""
    rng = np.random.RandomState(3000 + case)
    B = int(rng.choice([1, 2, 4]))
    T = int(rng.choice([2, 6, 9, 16, 24, 32]))
    D = int(rng.choice([3, 8]))
    H = int(rng.choice([4, 8, 16]))
    layers = int(rng.choice([1, 2]))
    use_mask = bool(rng.rand() < 0.5)
    bf16 = bool(rng.rand() < 0.3)
    cdtype = jnp.bfloat16 if bf16 else None

    keys = jax.random.split(jax.random.PRNGKey(case), layers)
    lp = [init_lstm_params(keys[0], D, H)]
    for k in keys[1:]:
        lp.append(init_lstm_params(k, H, H))
    xs = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    mask = None
    if use_mask:
        lens = rng.randint(1, T + 1, size=B)
        mask = jnp.asarray(
            (np.arange(T)[None, :] < lens[:, None]), jnp.float32
        )

    def loss(bptt):
        def L(args):
            params, x = args
            finals, ys = stacked_lstm_scan(
                params, x, mask=mask, bptt=bptt, compute_dtype=cdtype)
            out = jnp.sum(ys ** 2)
            for (h, c) in finals:
                out = out + jnp.sum(h) + 0.5 * jnp.sum(c)
            return out
        return L

    v_seq, g_seq = jax.value_and_grad(loss("sequential"))((lp, xs))
    v_asc, g_asc = jax.value_and_grad(loss("assoc"))((lp, xs))
    np.testing.assert_allclose(np.asarray(v_asc), np.asarray(v_seq),
                               rtol=1e-5, atol=1e-5)
    tol = (dict(rtol=3e-2, atol=3e-3) if bf16
           else dict(rtol=5e-4, atol=5e-5))
    for a, b in zip(jax.tree.leaves(g_asc), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **tol)
