"""Online serve autotuner (serve/autotune.py) + its satellites: knob
setters bounded by the warmed lattice, controller decisions from seeded
windowed deltas (hysteresis: no oscillation on flat workloads), zero
mid-traffic compiles with the controller live, --autotune-off parity,
the PR 10 burst gate with the controller on, per-tenant token-bucket
rate limiting, and the loadgen arrival modes (burst/sine/trace).
"""

import threading
import time

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.obs import MetricsRegistry
from lstm_tensorspark_tpu.serve import (
    AutoTuneConfig,
    AutoTuner,
    QueueFullError,
    ServeEngine,
    ServeServer,
    run_loadgen,
)
from lstm_tensorspark_tpu.serve.loadgen import arrival_offsets

_CFG = LMConfig(vocab_size=29, hidden_size=16, num_layers=1)


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(3), _CFG)


def _server(params, registry=None, *, session_dir=None, num_slots=8,
            host_tier_entries=4, tiered=False, **kw):
    reg = registry if registry is not None else MetricsRegistry()
    engine = ServeEngine(
        params, _CFG, num_slots=num_slots, prefill_buckets=(4, 8, 16),
        batch_buckets=(1, 2, 4), registry=reg,
        tiered_cache=tiered, host_tier_entries=host_tier_entries,
        session_dir=session_dir)
    kw.setdefault("max_active", 4)
    kw.setdefault("queue_size", 8)
    kw.setdefault("window_ladder", (1, 2, 4))
    return ServeServer(engine, **kw)


def _tuner(server, **cfg_kw):
    cfg_kw.setdefault("slo_s", 0.2)
    cfg_kw.setdefault("min_events", 4)
    cfg_kw.setdefault("patience_up", 2)
    cfg_kw.setdefault("patience_down", 1)
    cfg_kw.setdefault("cooldown", 0)
    return AutoTuner(server, AutoTuneConfig(**cfg_kw))


def _sig(*, itl=(0, None), qwait=(0, None), ttft=(0, None), queued=0,
         queue_size=8, chunks=0.0, tiers=None):
    def h(pair):
        count, p99 = pair
        out = {"count": count, "sum": 0.0}
        if p99 is not None:
            out["p50"] = p99 / 2
            out["p99"] = p99
        return out

    return {"ttft": h(ttft), "itl": h(itl), "queue_wait": h(qwait),
            "queued": queued, "queue_size": queue_size,
            "prefill_chunks": chunks, "tiers": tiers}


# the two canonical windows: ITL-bound steady decode (grow) and
# queue-wait-bound pressure (shrink) — p99s relative to slo_s = 0.2
_HEADROOM = dict(itl=(20, 0.002), qwait=(6, 0.001), ttft=(6, 0.005))
_PRESSURE = dict(itl=(20, 0.002), qwait=(8, 0.15), ttft=(8, 0.18))


# ---- knob setters: bounded by the warmed lattice -----------------------


def test_knob_setters_validate_and_stats_reflect(params):
    server = _server(params, prefill_chunk=4,
                     prefill_chunk_choices=(2, 4, 8))
    b = server.batcher
    assert b.window_cap == 4  # default: the top rung (pre-knob behavior)
    b.set_window_cap(2)
    assert b.stats()["window_cap"] == 2
    with pytest.raises(ValueError):
        b.set_window_cap(3)  # not a warmed ladder rung
    b.set_prefill_chunk(8)
    assert b.stats()["prefill_chunk"] == 8
    assert b.stats()["prefill_chunk_choices"] == [2, 4, 8]
    with pytest.raises(ValueError):
        b.set_prefill_chunk(6)  # not in the warmed choice set
    with pytest.raises(ValueError):
        # choices without chunking: the knob cannot turn chunking on
        _server(params, prefill_chunk_choices=(2, 4))


def test_warmup_covers_every_chunk_choice(params):
    """A knob move must never compile: warmup replays the chunk-stop
    sequence for EVERY choice, so traffic after any set_prefill_chunk
    finds its programs compiled."""
    server = _server(params, prefill_chunk=4,
                     prefill_chunk_choices=(2, 4, 8))
    with server:
        server.warmup(prompt_lens=(4, 8, 16))
        n0 = server.engine.num_compiles()
        for chunk in (2, 8, 4):
            server.batcher.set_prefill_chunk(chunk)
            server.generate(list(range(1, 11)), max_new_tokens=2)
        for cap in (1, 4, 2):
            server.batcher.set_window_cap(cap)
            server.generate([1, 2, 3], max_new_tokens=6)
        assert server.engine.num_compiles() == n0


# ---- controller decisions (seeded windows; tick() driven directly) -----


def test_warmup_covers_mid_prefill_chunk_mixes(params):
    """A knob move can land BETWEEN a long prompt's chunk dispatches, so
    one prompt may mix chunk sizes — segment lengths neither pure-choice
    replay produces (chunk 4 then 8 on a 16-token prompt ends with an
    8-length final from position 4+8=12... and a 4+8 intermediate walk).
    The warmup closure must cover every mix."""
    from lstm_tensorspark_tpu.serve import Request

    server = _server(params, prefill_chunk=4,
                     prefill_chunk_choices=(2, 4, 8))
    b = server.batcher
    b.warmup(prompt_lens=(4, 8, 16))
    n0 = server.engine.num_compiles()
    req = Request(list(range(1, 17)), 2)
    b.submit(req)
    b.step()  # dispatches the first chunk at size 4
    b.set_prefill_chunk(8)  # the controller moves mid-prompt
    b.drain()
    assert req.error is None and len(req.tokens) == 2
    assert server.engine.num_compiles() == n0


def test_tuner_moves_k_up_on_itl_bound_windows(params):
    server = _server(params)
    server.batcher.set_window_cap(2)  # mid-ladder operating point
    tuner = _tuner(server)
    assert tuner.tick(_sig(**_HEADROOM)) == []  # patience_up = 2
    moves = tuner.tick(_sig(**_HEADROOM))
    assert [(m["knob"], m["direction"]) for m in moves] == [
        ("window_k", "up")]
    assert server.batcher.window_cap == 4
    # at the top rung: further headroom windows cannot overshoot
    for _ in range(4):
        tuner.tick(_sig(**_HEADROOM))
    assert server.batcher.window_cap == 4


def test_tuner_moves_k_down_on_queue_pressure(params):
    server = _server(params)
    tuner = _tuner(server)
    moves = tuner.tick(_sig(**_PRESSURE))  # patience_down = 1
    assert moves and {k: moves[0][k] for k in
                      ("knob", "direction", "from", "to")} == {
        "knob": "window_k", "direction": "down", "from": 4, "to": 2}
    tuner.tick(_sig(**_PRESSURE))
    assert server.batcher.window_cap == 1
    for _ in range(3):  # floor: never below rung 1
        tuner.tick(_sig(**_PRESSURE))
    assert server.batcher.window_cap == 1
    s = tuner.stats()
    assert s["moves"]["window_k"]["down"] == 2
    assert s["window"]["pressure"] is True


def test_tuner_hysteresis_no_moves_on_flat_or_sparse_windows(params):
    """A quiet server (no samples), a sparse window (below min_events),
    and alternating one-window signals must never move a knob — the
    no-oscillation contract."""
    server = _server(params)
    server.batcher.set_window_cap(2)
    tuner = _tuner(server, patience_up=2, patience_down=2)
    for _ in range(6):
        assert tuner.tick(_sig()) == []  # flat: no traffic at all
    sparse = dict(_HEADROOM)
    sparse["itl"] = (2, 0.002)  # below min_events: casts no vote
    for _ in range(6):
        assert tuner.tick(_sig(**sparse)) == []
    for _ in range(4):  # alternating: the streak resets every window
        assert tuner.tick(_sig(**_HEADROOM)) == []
        assert tuner.tick(_sig(**_PRESSURE)) == []
    assert server.batcher.window_cap == 2
    assert tuner.stats()["moves"]["window_k"] == {"up": 0, "down": 0}


def test_tuner_cooldown_rests_after_a_move(params):
    server = _server(params)
    tuner = _tuner(server, cooldown=2, patience_down=1)
    assert tuner.tick(_sig(**_PRESSURE))  # 4 -> 2
    assert tuner.tick(_sig(**_PRESSURE)) == []  # cooling
    assert tuner.tick(_sig(**_PRESSURE)) == []  # cooling
    assert tuner.tick(_sig(**_PRESSURE))  # 2 -> 1
    assert server.batcher.window_cap == 1


def test_tuner_moves_chunk_opposite_to_k(params):
    """Pressure grows the chunk (finish prompts in fewer dispatches);
    ITL-bound headroom shrinks it (bound the stall) — and the knob only
    moves while prefill chunks are actually dispatching."""
    server = _server(params, prefill_chunk=4,
                     prefill_chunk_choices=(2, 4, 8))
    tuner = _tuner(server)
    # no prefill activity in the window: the chunk knob stays pinned
    tuner.tick(_sig(**_PRESSURE))
    assert server.batcher.prefill_chunk == 4
    moves = tuner.tick(_sig(**_PRESSURE, chunks=3.0))
    assert ("prefill_chunk", "up") in {(m["knob"], m["direction"])
                                       for m in moves}
    assert server.batcher.prefill_chunk == 8  # pressure: larger chunks
    tuner2 = _tuner(server)
    tuner2.tick(_sig(**_HEADROOM, chunks=3.0))
    moves = tuner2.tick(_sig(**_HEADROOM, chunks=3.0))
    assert ("prefill_chunk", "down") in {(m["knob"], m["direction"])
                                         for m in moves}
    assert server.batcher.prefill_chunk == 4  # headroom: bound the stall


def test_tuner_grows_host_tier_on_spill_thrash_and_shrinks_back(params,
                                                                tmp_path):
    server = _server(params, tiered=True, host_tier_entries=4,
                     session_dir=str(tmp_path))
    tuner = _tuner(server, host_tier_max=16, patience_down=1,
                   patience_up=2)
    thrash = {"host": 4, "host_max": 4, "disk_spills": 3.0,
              "disk_fills": 2.0, "lost": 0.0}
    moves = tuner.tick(_sig(tiers=thrash))
    assert moves and moves[0]["knob"] == "host_tier"
    assert moves[0]["direction"] == "up"
    assert server.engine.tiers.host_entries == 8
    # grow caps at host_tier_max
    tuner.tick(_sig(tiers={**thrash, "host": 8, "host_max": 8}))
    assert server.engine.tiers.host_entries == 16
    for _ in range(3):
        tuner.tick(_sig(tiers={**thrash, "host": 16, "host_max": 16}))
    assert server.engine.tiers.host_entries == 16
    # occupancy collapses: shrink back toward the configured size only
    idle = {"host": 1, "host_max": 16, "disk_spills": 0.0,
            "disk_fills": 0.0, "lost": 0.0}
    for _ in range(8):
        tuner.tick(_sig(tiers=idle))
    assert server.engine.tiers.host_entries == 4  # never below initial


def test_tuner_tightens_best_effort_at_capacity_ceiling(params, tmp_path):
    server = _server(params, tiered=True, host_tier_entries=4,
                     session_dir=str(tmp_path))
    tuner = _tuner(server, host_tier_max=4, patience_down=1,
                   patience_up=2, best_effort_floor=0.1)
    thrash = {"host": 4, "host_max": 4, "disk_spills": 3.0,
              "disk_fills": 2.0, "lost": 1.0}
    # tier already at max (host_tier_max == initial): tighten admission
    moves = tuner.tick(_sig(tiers=thrash))
    assert ("best_effort", "down") in {(m["knob"], m["direction"])
                                       for m in moves}
    assert server.router.best_effort_frac == 0.25
    for _ in range(4):
        tuner.tick(_sig(tiers=thrash))
    assert server.router.best_effort_frac >= 0.1  # floor respected
    # thrash clears: relax back toward the configured policy
    idle = {"host": 0, "host_max": 4, "disk_spills": 0.0,
            "disk_fills": 0.0, "lost": 0.0}
    for _ in range(8):
        tuner.tick(_sig(tiers=idle))
    assert server.router.best_effort_frac == 0.5  # never above initial


# ---- live-stack integration -------------------------------------------


def test_controller_live_zero_mid_traffic_compiles(params):
    """Real traffic with the controller thread live and knobs forced
    through their whole range: serve_compiles_total must not move after
    warmup — the controller can NEVER trigger a mid-traffic compile."""
    reg = MetricsRegistry()
    server = _server(params, registry=reg, prefill_chunk=4,
                     prefill_chunk_choices=(2, 4, 8),
                     autotune=AutoTuneConfig(interval_s=0.02, slo_s=0.05,
                                             min_events=2, patience_up=1,
                                             patience_down=1, cooldown=0))
    with server:
        server.warmup(prompt_lens=(4, 8, 16))
        n0 = server.engine.num_compiles()
        assert server.autotuner._thread is not None  # controller live
        for i in range(12):
            server.generate(list(range(1, 4 + (i % 12))),
                            max_new_tokens=5)
        assert server.engine.num_compiles() == n0
        st = server.stats()["autotune"]
        assert st["ticks"] > 0 and st["errors"] == 0
        # whatever the controller chose, it stayed inside the lattice
        assert server.batcher.window_cap in server.batcher.window_ladder
        assert (server.batcher.prefill_chunk
                in server.batcher.prefill_chunk_choices)
    assert server.autotuner._thread is None  # joined by stop()


def test_autotune_off_is_todays_behavior(params):
    """No config = no controller thread, no knob ever moves, and greedy
    tokens are identical to an autotuned stack's (the knobs change
    latency shape, never output)."""
    server_off = _server(params)
    server_on = _server(params,
                        autotune=AutoTuneConfig(interval_s=0.02))
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
    outs = {}
    for name, server in (("off", server_off), ("on", server_on)):
        with server:
            server.warmup(prompt_lens=(4,))
            outs[name] = [list(server.generate(
                p, max_new_tokens=6).tokens) for p in prompts]
    assert outs["off"] == outs["on"]
    assert server_off.autotuner is None
    assert server_off.stats()["autotune"] is None
    assert server_off.batcher.window_cap == 4  # untouched top rung


def test_burst_gate_holds_with_controller_on(params):
    """The PR 10 SLO-aware shedding contract survives a live controller:
    under an over-capacity open-loop burst, zero PRIORITY sheds while
    best-effort sheds with Retry-After."""
    # bounds sized so PRIORITY structurally cannot shed (12 priority
    # requests + the best-effort bound < queue_size) while best-effort
    # must: the test gates the POLICY with the controller live, not CPU
    # scheduling luck. The arrival rate compresses the whole burst into
    # ~10 ms — at 500/s a single-core box sometimes DRAINS best-effort
    # under its bound between arrivals and nothing sheds (the priority
    # invariant is rate-independent: 12 + bound(5) < 24 at any rate)
    server = _server(params, queue_size=24, best_effort_queue_frac=0.2,
                     autotune=AutoTuneConfig(interval_s=0.02, slo_s=0.25,
                                             min_events=4))
    with server:
        server.warmup(prompt_lens=(4,))
        report = run_loadgen(
            server, vocab_size=_CFG.vocab_size, sessions=4,
            requests_per_session=12, prompt_len=4, max_new_tokens=8,
            mode="open", rate=5000.0, priority_frac=0.25, seed=7,
            retry_max=1, retry_base_s=0.02, retry_cap_s=0.2)
    assert report["classes"]["priority"]["shed"] == 0
    assert report["classes"]["best_effort"]["shed"] >= 1
    assert report["classes"]["priority"]["completed"] >= 1


def test_moves_metric_exported(params):
    reg = MetricsRegistry()
    server = _server(params, registry=reg)
    tuner = _tuner(server, patience_down=1)
    tuner.tick(_sig(**_PRESSURE))
    s = reg.summaries()
    key = 'serve_autotune_moves_total{knob="window_k",direction="down"}'
    assert s[key] == 1
    st = tuner.stats()
    assert st["history"][-1]["knob"] == "window_k"
    assert st["knobs"]["window_k"]["value"] == 2


# ---- per-tenant rate limiting ------------------------------------------


def test_tenant_token_bucket_sheds_with_retry_after(params):
    reg = MetricsRegistry()
    server = _server(params, tenant_rate=1.0, tenant_burst=2.0,
                     registry=reg)
    with server:
        server.warmup(prompt_lens=(4,))
        for _ in range(2):  # the burst allowance admits these
            server.generate([1, 2, 3], max_new_tokens=2, tenant="acme")
        with pytest.raises(QueueFullError) as ei:
            server.generate([1, 2, 3], max_new_tokens=2, tenant="acme")
        assert ei.value.retry_after_s and ei.value.retry_after_s > 0
        # a DIFFERENT tenant and untenanted traffic are unaffected
        server.generate([1, 2, 3], max_new_tokens=2, tenant="other")
        server.generate([1, 2, 3], max_new_tokens=2)
    st = server.router.stats()
    assert st["tenant_limited"] == {"priority": 1, "best_effort": 0}
    assert st["tenant_rate"] == 1.0
    s = reg.summaries()
    assert s['serve_shed_total{class="priority",tenant_limited="yes"}'] == 1
    assert s["serve_retry_after_seconds"]["count"] == 1


def test_tenant_bucket_refills_over_time(params):
    server = _server(params, tenant_rate=50.0, tenant_burst=1.0)
    with server:
        server.warmup(prompt_lens=(4,))
        server.generate([1, 2], max_new_tokens=2, tenant="t")
        with pytest.raises(QueueFullError):
            server.generate([1, 2], max_new_tokens=2, tenant="t")
        time.sleep(0.05)  # > 1/rate: one token accrued
        server.generate([1, 2], max_new_tokens=2, tenant="t")


def test_tenant_bucket_table_hard_bounded(params):
    """A flood of FRESH tenant names faster than the refill rate must not
    grow the bucket table past MAX_TENANT_BUCKETS: with nothing fully
    refilled to prune, the fullest bucket is evicted instead — the cap
    is a memory bound, not a hint."""
    server = _server(params, tenant_rate=0.001, tenant_burst=2.0)
    router = server.router
    cap = 8
    router.MAX_TENANT_BUCKETS = cap
    with router._lock:
        for i in range(3 * cap):  # refill needs ~1000 s: never prunable
            router._tenant_take_locked(f"flood-{i}")
            assert len(router._tenant_buckets) <= cap
    assert len(router._tenant_buckets) == cap


def test_tenant_rate_off_by_default(params):
    server = _server(params)
    with server:
        server.warmup(prompt_lens=(4,))
        for _ in range(3):
            server.generate([1, 2], max_new_tokens=2, tenant="acme")
    assert server.router.stats()["tenant_limited"] == {
        "priority": 0, "best_effort": 0}


# ---- loadgen arrival modes ---------------------------------------------


def test_arrival_offsets_shapes():
    # burst: groups of burst_n at each gap, simultaneous within a burst
    off = arrival_offsets(6, arrival="burst", burst_n=3, burst_gap_s=0.5)
    assert off == [0.0, 0.0, 0.0, 0.5, 0.5, 0.5]
    # fixed: the classic constant rate
    assert arrival_offsets(3, rate=10.0) == [0.0, 0.1, 0.2]
    # sine: non-decreasing, rate modulated around the mean — the gap at
    # peak rate is shorter than at trough rate
    off = arrival_offsets(40, rate=20.0, arrival="sine",
                          sine_period_s=1.0, sine_amp=0.5)
    gaps = [b - a for a, b in zip(off, off[1:])]
    assert all(g > 0 for g in gaps)
    assert min(gaps) < 1 / 20.0 < max(gaps)
    # trace replay: explicit offsets; a short trace LOOPS shifted by its
    # span (the recorded shape repeats instead of truncating)
    off = arrival_offsets(5, arrival_times=[0.0, 0.1])
    assert off[:2] == [0.0, 0.1]
    assert off[2] > off[1] and off[4] > off[3]
    with pytest.raises(ValueError):
        arrival_offsets(2, arrival_times=[0.2, 0.1])  # unsorted
    with pytest.raises(ValueError):
        # a burst spanning past the next burst's start would silently
        # degenerate into a continuous stream — refused, not misreported
        arrival_offsets(16, rate=20.0, arrival="burst", burst_n=8,
                        burst_gap_s=0.2)
    with pytest.raises(ValueError):
        arrival_offsets(2, arrival="fixed")  # fixed needs a rate
    with pytest.raises(ValueError):
        arrival_offsets(2, arrival="warp")


def test_loadgen_trace_replay_drives_requests(params):
    server = _server(params)
    trace = [0.0, 0.01, 0.02, 0.25]
    with server:
        server.warmup(prompt_lens=(4,))
        report = run_loadgen(
            server, vocab_size=_CFG.vocab_size, sessions=2,
            requests_per_session=2, prompt_len=4, max_new_tokens=2,
            mode="open", arrival_times=trace, seed=11)
    assert report["arrival"] == "trace"
    assert report["arrival_trace_len"] == 4
    assert report["completed"] == 4
    # arrival shaping is an open-loop feature
    with pytest.raises(ValueError):
        run_loadgen(server, vocab_size=_CFG.vocab_size,
                    mode="closed", arrival="burst")
