"""obs/: metrics registry, instruments, Prometheus exposition contract.

Covers the ISSUE-5 /metrics test checklist: exposition-format validity
(types declared, parseable samples, bucket monotonicity, _sum/_count
consistency), histogram correctness under concurrent recording, and the
quantile estimator's bucket-level accuracy.
"""

import math
import threading

import pytest

from lstm_tensorspark_tpu.obs import (
    DEFAULT_LATENCY_BUCKETS,
    NULL_REGISTRY,
    Histogram,
    MetricsRegistry,
    parse_exposition,
)


def test_counter_and_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("jobs_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(7)
    g.inc()
    g.dec(3)
    assert g.value == 5.0


def test_registration_is_idempotent_but_kind_safe():
    reg = MetricsRegistry()
    a = reg.counter("x_total")
    assert reg.counter("x_total") is a  # same family back
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # one name, one meaning
    with pytest.raises(ValueError):
        reg.counter("x_total", labelnames=("k",))  # labelset is part of it
    with pytest.raises(ValueError):
        reg.counter("bad name")
    h = reg.histogram("lat", buckets=(1.0, 2.0))
    assert reg.histogram("lat", buckets=(1.0, 2.0)) is h
    with pytest.raises(ValueError):
        reg.histogram("lat", buckets=(5.0,))  # silently requantizing the
        # second caller's observations would be the same one-name lie
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=(1.0, 1.0))  # not strictly increasing
    with pytest.raises(ValueError):
        reg.histogram("h2", buckets=(1.0, float("inf")))  # +Inf is implicit


def test_labels():
    reg = MetricsRegistry()
    fam = reg.counter("req_total", labelnames=("outcome",))
    fam.labels(outcome="ok").inc(3)
    fam.labels(outcome="err").inc()
    assert fam.labels(outcome="ok").value == 3
    with pytest.raises(ValueError):
        fam.labels(wrong="x")
    text = reg.render_prometheus()
    assert 'req_total{outcome="ok"} 3' in text
    assert 'req_total{outcome="err"} 1' in text


def test_histogram_buckets_sum_count():
    h = Histogram(buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 2.0, 100.0):  # 0.1 lands IN le=0.1 (inclusive)
        h.observe(v)
    counts, s, total = h.snapshot()
    assert counts == [2, 1, 1, 1]  # last = +Inf overflow
    assert total == 5 and abs(s - 102.65) < 1e-9
    summ = h.summary()
    assert summ["count"] == 5 and 0.1 <= summ["p50"] <= 1.0


def test_quantile_lands_in_the_right_bucket():
    h = Histogram(buckets=DEFAULT_LATENCY_BUCKETS)
    for _ in range(100):
        h.observe(0.003)  # bucket (0.0025, 0.005]
    assert 0.0025 <= h.quantile(0.5) <= 0.005
    assert 0.0025 <= h.quantile(0.99) <= 0.005
    empty = Histogram()
    assert math.isnan(empty.quantile(0.5))
    # overflow-only mass clamps to the largest finite bound
    h2 = Histogram(buckets=(1.0,))
    h2.observe(50.0)
    assert h2.quantile(0.5) == 1.0


def test_histogram_concurrent_recording():
    """N threads hammering one histogram must lose nothing: count, sum,
    and the bucket totals all reconcile."""
    h = Histogram(buckets=(0.5, 1.5, 2.5))
    per_thread, n_threads = 1998, 8  # divisible by 3: exact bucket splits

    def work(seed):
        for i in range(per_thread):
            h.observe((seed + i) % 3)  # values 0, 1, 2 round-robin

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    counts, s, total = h.snapshot()
    expect = per_thread * n_threads
    assert total == expect
    assert sum(counts) == expect
    assert abs(s - sum((i % 3) for i in range(3)) * expect / 3) < 1e-6
    # values 0/1/2 split evenly across the first three buckets
    assert counts[:3] == [expect // 3] * 3 and counts[3] == 0


def test_exposition_parses_and_validates():
    reg = MetricsRegistry()
    reg.counter("a_total", "things").inc(2)
    reg.gauge("b", "level").set(-1.5)
    hist = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    hist.observe(0.05)
    hist.observe(5.0)
    fam = reg.histogram("lab_seconds", labelnames=("k",), buckets=(1.0,))
    fam.labels(k="4").observe(0.5)
    text = reg.render_prometheus()
    fams = parse_exposition(text)  # raises on any format violation
    assert fams["a_total"]["type"] == "counter"
    assert ("a_total", {}, 2.0) in fams["a_total"]["samples"]
    assert ("b", {}, -1.5) in fams["b"]["samples"]
    hs = {name: (labels, v)
          for name, labels, v in fams["lat_seconds"]["samples"]}
    assert hs["lat_seconds_count"][1] == 2.0
    assert abs(hs["lat_seconds_sum"][1] - 5.05) < 1e-9
    buckets = [(labels["le"], v) for name, labels, v
               in fams["lat_seconds"]["samples"]
               if name == "lat_seconds_bucket"]
    assert buckets == [("0.1", 1.0), ("1", 1.0), ("+Inf", 2.0)]
    # labelled histogram series round-trips too
    assert any(labels.get("k") == "4"
               for _, labels, _ in fams["lab_seconds"]["samples"])


@pytest.mark.parametrize("bad", [
    "no_type_decl 1",                                   # sample without TYPE
    "# TYPE h histogram\nh_bucket{le=\"1\"} 2\nh_bucket{le=\"+Inf\"} 1\n"
    "h_sum 1\nh_count 1",                               # buckets decrease
    "# TYPE h histogram\nh_bucket{le=\"1\"} 1\nh_sum 1\nh_count 1",  # no +Inf
    "# TYPE h histogram\nh_bucket{le=\"+Inf\"} 3\nh_sum 1\nh_count 2",  # count
    "# TYPE c counter\nc{=} 1",                         # bad label block
    "# TYPE c counter\nc one",                          # bad value
])
def test_exposition_validator_rejects(bad):
    with pytest.raises(ValueError):
        parse_exposition(bad)


def test_snapshot_delta_histogram_windows():
    """snapshot_delta: the delta view describes ONLY the samples recorded
    since the cursor — the recent-biased quantiles a controller steers on
    (the cumulative summary() would keep reporting boot-time history)."""
    reg = MetricsRegistry()
    fam = reg.histogram("lat_seconds", labelnames=("replica",))
    fam.labels(replica="0").observe(0.001)
    view, cur = fam.snapshot_delta(None)  # None = since registration
    assert view["count"] == 1
    assert view["p99"] <= 0.001
    # new window: only the fresh (much slower) samples show up, merged
    # across children — including a child born mid-window
    fam.labels(replica="0").observe(0.1)
    fam.labels(replica="1").observe(0.1)
    view, cur = fam.snapshot_delta(cur)
    assert view["count"] == 2
    assert 0.05 <= view["p50"] <= 0.1  # the old 1 ms sample is gone
    assert abs(view["sum"] - 0.2) < 1e-9
    # an empty window reports zero, not the lifetime distribution
    view, cur = fam.snapshot_delta(cur)
    assert view == {"count": 0, "sum": 0.0}
    # the lifetime summary still covers everything (cursors are
    # per-consumer: reading a delta resets nothing)
    assert fam.labels(replica="0").summary()["count"] == 2


def test_snapshot_delta_counter_and_gauge():
    reg = MetricsRegistry()
    c = reg.counter("jobs_total", labelnames=("k",))
    c.labels(k="a").inc(3)
    v, cur = c.snapshot_delta()
    assert v == 3.0
    c.labels(k="a").inc(2)
    c.labels(k="b").inc(1)
    v, cur = c.snapshot_delta(cur)
    assert v == 3.0  # 2 on the old child + 1 on the new one
    v, cur = c.snapshot_delta(cur)
    assert v == 0.0
    # gauges are levels, not flows: the view is the current summed value
    g = reg.gauge("depth", labelnames=("k",))
    g.labels(k="a").set(7)
    v, gcur = g.snapshot_delta()
    assert v == 7.0
    v, gcur = g.snapshot_delta(gcur)
    assert v == 7.0


def test_snapshot_delta_independent_consumers():
    """Two consumers with their own cursors see the same windows — a
    delta read must never reset another reader (unlike read-and-clear)."""
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds")
    h.observe(0.01)
    va, ca = h.snapshot_delta()
    vb, cb = h.snapshot_delta()
    assert va["count"] == vb["count"] == 1
    h.observe(0.02)
    va, ca = h.snapshot_delta(ca)
    vb, cb = h.snapshot_delta(cb)
    assert va["count"] == vb["count"] == 1


def test_null_registry_noops():
    c = NULL_REGISTRY.counter("x", "whatever")
    c.inc()
    c.labels(a="b").inc(5)
    assert c.value == 0.0
    h = NULL_REGISTRY.histogram("h")
    h.observe(1.0)
    assert h.summary() == {}
    view, cur = h.snapshot_delta()
    assert view["count"] == 0 and cur == {}
    assert NULL_REGISTRY.summaries() == {}
    assert "disabled" in NULL_REGISTRY.render_prometheus()


def test_snapshot_flattens_for_jsonl():
    reg = MetricsRegistry()
    reg.counter("c_total").inc(4)
    h = reg.histogram("h_seconds", buckets=(1.0, 2.0))
    h.observe(0.5)
    snap = reg.snapshot()
    assert snap["c_total"] == 4
    assert snap["h_seconds_count"] == 1
    assert "h_seconds_p50" in snap and "h_seconds_p99" in snap
