"""Supervisor (supervise.py): restart-with-resume semantics via an injected
runner, plus a real crash-and-resume integration through the CLI."""

import json

from lstm_tensorspark_tpu.supervise import supervise


def test_success_first_try_no_resume():
    calls = []

    def runner(argv):
        calls.append(list(argv))
        return 0

    assert supervise(["--x"], runner=runner) == 0
    assert calls == [["--x"]]


def test_restart_injects_resume_then_succeeds():
    calls = []

    def runner(argv):
        calls.append(list(argv))
        return 1 if len(calls) < 3 else 0

    rc = supervise(["--a", "--checkpoint-dir", "d"], max_restarts=5,
                   restart_delay=0.0, runner=runner)
    assert rc == 0
    assert calls[0] == ["--a", "--checkpoint-dir", "d"]
    assert calls[1] == ["--a", "--checkpoint-dir", "d", "--resume"]
    assert calls[2] == calls[1]


def test_serve_child_relaunches_without_resume():
    """A supervised SERVE child (``supervise -- serve --http ...``) must
    be relaunched with its argv UNTOUCHED: serve's parser has no
    --resume (argparse would exit 2 → wrongly classified deterministic),
    and session continuity comes from serve's own --session-dir disk
    tier instead."""
    calls = []

    def runner(argv):
        calls.append(list(argv))
        return 1 if len(calls) < 3 else 0

    argv = ["serve", "--http", "--session-dir", "d",
            "--checkpoint-dir", "ck"]
    rc = supervise(argv, max_restarts=5, restart_delay=0.0, runner=runner)
    assert rc == 0
    assert calls == [argv, argv, argv]  # never mutated, never --resume'd


def test_gives_up_after_max_restarts():
    calls = []

    def runner(argv):
        calls.append(argv)
        return 7

    rc = supervise(["--a"], max_restarts=2, restart_delay=0.0, runner=runner)
    assert rc == 7  # the last failing child's exit code, not a sentinel
    assert len(calls) == 3  # first attempt + 2 restarts


def test_resume_not_duplicated():
    calls = []

    def runner(argv):
        calls.append(list(argv))
        return 1 if len(calls) < 2 else 0

    supervise(["--resume"], max_restarts=2, restart_delay=0.0, runner=runner)
    assert calls[1].count("--resume") == 1


def test_crash_resume_integration(tmp_path):
    """Real CLI child: first run checkpoints then 'crashes' (in-process
    runner truncates the budget); the supervised rerun resumes from the
    checkpoint and finishes the step budget exactly."""
    from lstm_tensorspark_tpu.cli import main as cli_main

    ckpt = tmp_path / "ckpt"
    jsonl = tmp_path / "m.jsonl"
    base = [
        "--dataset", "ptb_char", "--hidden-units", "16", "--batch-size", "8",
        "--backend", "single", "--num-steps", "6", "--log-every", "1",
        "--checkpoint-dir", str(ckpt), "--checkpoint-every", "2",
        "--jsonl", str(jsonl),
    ]
    attempts = []

    def runner(argv):
        attempts.append(list(argv))
        if len(attempts) == 1:
            # simulate a crash: run only part of the budget, then fail
            cli_main([*argv[:argv.index("--num-steps")], "--num-steps", "4",
                      *argv[argv.index("--num-steps") + 2:]])
            return 1
        return cli_main(argv)

    rc = supervise(base, max_restarts=1, restart_delay=0.0, runner=runner)
    assert rc == 0
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert any("resumed at step 4" in str(r.get("note", "")) for r in records)
    finals = [r for r in records if r.get("note") == "final"]
    assert finals[-1]["step"] == 6  # budget is resume-inclusive


def test_usage_error_not_retried():
    """Exit code 2 (argparse usage error) is deterministic — retrying burns
    the restart budget on a run that can never succeed."""
    calls = []

    def runner(argv):
        calls.append(argv)
        return 2

    rc = supervise(["--bogus"], max_restarts=5, restart_delay=0.0,
                   runner=runner)
    assert rc == 2
    assert len(calls) == 1  # no retries


def test_signal_death_maps_to_128_plus_signum():
    def runner(argv):
        return -9  # subprocess convention for SIGKILL

    rc = supervise(["--a"], max_restarts=0, restart_delay=0.0, runner=runner)
    assert rc == 137  # 128 + 9


def test_stall_watch_kills_silent_child():
    import sys
    import time

    from lstm_tensorspark_tpu.supervise import run_with_stall_watch

    t0 = time.monotonic()
    rc = run_with_stall_watch(
        [sys.executable, "-c",
         "print('hello', flush=True); import time; time.sleep(300)"],
        stall_timeout=5.0,
    )
    assert rc < 0, rc  # signal death: the watchdog fired
    assert time.monotonic() - t0 < 120


def test_stall_watch_passes_healthy_child_through():
    import sys

    from lstm_tensorspark_tpu.supervise import run_with_stall_watch

    # VERY generous timeout vs tick gap: the healthy path returns as soon
    # as the child exits (~1.2s), so the timeout's size costs nothing —
    # and the suite may share the machine with heavy load (observed: a
    # concurrent benchmark delayed a fresh interpreter's startup past a
    # 15s window, faking a stall). A loaded scheduler must not flake this.
    rc = run_with_stall_watch(
        [sys.executable, "-c",
         "import time\n"
         "for i in range(4):\n"
         "    print('tick', i, flush=True); time.sleep(0.3)\n"],
        stall_timeout=60.0,
    )
    assert rc == 0


def test_supervise_retries_stall_deaths():
    """A watchdog kill surfaces as a signal death (rc >= 128 after
    conversion) and must be retried, not classed as deterministic."""
    from lstm_tensorspark_tpu.supervise import supervise

    calls = []

    def runner(argv):
        calls.append(list(argv))
        return -15 if len(calls) == 1 else 0  # stalled once, then healthy

    rc = supervise(["--checkpoint-dir", "x"], max_restarts=2,
                   restart_delay=0.0, runner=runner)
    assert rc == 0
    assert len(calls) == 2 and "--resume" in calls[1]


def test_stall_timeout_must_be_positive():
    import pytest

    from lstm_tensorspark_tpu.supervise import supervise

    for bad in (0.0, -60.0):
        with pytest.raises(SystemExit):
            supervise(["--checkpoint-dir", "x"], stall_timeout=bad,
                      runner=lambda argv: 0)


def test_backoff_delay_exponential_capped_jittered():
    from lstm_tensorspark_tpu.supervise import backoff_delay

    assert backoff_delay(1.0, 1, rand=lambda: 0.0) == 1.0
    assert backoff_delay(1.0, 3, rand=lambda: 0.0) == 4.0
    assert backoff_delay(1.0, 10, cap=30.0, rand=lambda: 0.0) == 30.0
    assert backoff_delay(1.0, 1, rand=lambda: 1.0) == 1.5  # +50% max jitter
    # the cap bounds the SLEPT delay, jitter included
    assert backoff_delay(1.0, 10, cap=30.0, rand=lambda: 1.0) == 30.0
    assert backoff_delay(0.0, 5, rand=lambda: 1.0) == 0.0  # tests' fast path


def test_poison_when_checkpoints_stop_advancing(tmp_path):
    """A crash loop that never advances the latest checkpoint step must end
    with the dedicated poison rc, not grind through the restart budget."""
    from lstm_tensorspark_tpu.resilience.exit_codes import POISON_RC

    calls = []

    def runner(argv):
        calls.append(1)
        # checkpoints exist but are STUCK at step 2 across every failure
        (tmp_path / "step_2.msgpack").write_bytes(b"x")
        return 9

    rc = supervise(["--checkpoint-dir", str(tmp_path)], max_restarts=10,
                   restart_delay=0.0, runner=runner)
    assert rc == POISON_RC
    # baseline failure + 2 consecutive no-progress failures (default limit)
    assert len(calls) == 3


def test_never_checkpointed_run_is_not_poisoned(tmp_path):
    """No checkpoint has ever been written (first interval still open, or
    --checkpoint-every 0 with the dir only holding fault markers): there
    is nothing to measure progress by, so transient crashes must get the
    full restart budget, not an early poison verdict."""
    calls = []

    def runner(argv):
        calls.append(1)
        return 9  # fails, dir stays empty

    rc = supervise(["--checkpoint-dir", str(tmp_path)], max_restarts=3,
                   restart_delay=0.0, runner=runner)
    assert rc == 9  # the child's own rc after the full budget
    assert len(calls) == 4


def test_signal_deaths_never_count_toward_poison(tmp_path):
    """Preemption/OOM-kill/stall-kill (rc >= 128) are the transient class:
    repeated signal deaths inside one checkpoint interval must burn the
    normal restart budget, not trip the poison detector."""
    calls = []

    def runner(argv):
        calls.append(1)
        return -9  # SIGKILL every time, checkpoint never advances

    rc = supervise(["--checkpoint-dir", str(tmp_path)], max_restarts=5,
                   restart_delay=0.0, runner=runner)
    assert rc == 137  # exhausted budget with the child's own code
    assert len(calls) == 6  # full budget, no early poison


def test_checkpoint_progress_resets_poison_counter(tmp_path):
    """As long as each failure leaves a NEWER checkpoint than the last, the
    supervisor keeps retrying to its normal budget (and then returns the
    child's own rc, not poison)."""
    calls = []

    def runner(argv):
        calls.append(1)
        (tmp_path / f"step_{len(calls) * 2}.msgpack").write_bytes(b"x")
        return 9

    rc = supervise(["--checkpoint-dir", str(tmp_path)], max_restarts=3,
                   restart_delay=0.0, runner=runner)
    assert rc == 9
    assert len(calls) == 4  # first attempt + full 3-restart budget


def test_latest_checkpoint_step_scan(tmp_path):
    from lstm_tensorspark_tpu.supervise import latest_checkpoint_step

    assert latest_checkpoint_step(str(tmp_path / "missing")) is None
    assert latest_checkpoint_step(str(tmp_path)) is None
    (tmp_path / "step_4.msgpack").write_bytes(b"x")
    (tmp_path / "step_8.complete").write_bytes(b"2")  # sharded marker
    (tmp_path / "step_12.msgpack.quarantined").write_bytes(b"x")  # corrupt
    (tmp_path / "step_6.msgpack.sha256").write_bytes(b"x")  # sidecar only
    assert latest_checkpoint_step(str(tmp_path)) == 8


def test_retryable_rcs_exempt_from_fast_death_heuristic():
    """An injected-crash or anomaly-abort child can die in <1s on tiny CPU
    runs; the deterministic-failure heuristic must still retry it."""
    from lstm_tensorspark_tpu.resilience.exit_codes import (
        ANOMALY_RC,
        FAULT_CRASH_RC,
        RETRYABLE_RCS,
    )
    from lstm_tensorspark_tpu.supervise import _deterministic_failure

    for rc in (FAULT_CRASH_RC, ANOMALY_RC, *RETRYABLE_RCS):
        assert not _deterministic_failure(rc, 0.1, True)
    assert _deterministic_failure(2, 5.0, True)       # usage error: always
    assert _deterministic_failure(1, 0.1, True)       # fast unknown death
    assert not _deterministic_failure(1, 5.0, True)   # slow death: retry
    assert not _deterministic_failure(137, 0.1, True)  # signal: retry
    assert not _deterministic_failure(1, 0.1, False)  # injected runner


def test_resume_best_converted_to_resume_on_relaunch():
    """--resume-best is a one-time rewind: relaunches must continue the
    fine-tune's own lineage via plain --resume."""
    from lstm_tensorspark_tpu.supervise import supervise

    calls = []

    def runner(argv):
        calls.append(list(argv))
        return 1 if len(calls) == 1 else 0

    rc = supervise(["--checkpoint-dir", "x", "--resume-best"],
                   max_restarts=2, restart_delay=0.0, runner=runner)
    assert rc == 0
    assert "--resume-best" in calls[0]
    assert "--resume-best" not in calls[1] and "--resume" in calls[1]
