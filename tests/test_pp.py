"""Pipeline parallelism (DP x PP wavefront over stacked layers): exact loss
and parameter parity with the single-device step over several steps."""

import jax
import numpy as np

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_mesh
from lstm_tensorspark_tpu.parallel.pipeline_parallel import (
    make_pp_lm_train_step,
    place_pp_lm_params,
    stack_lm_params,
    unstack_lm_params,
)
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 11, 16, 8, 12


def _batches(n, seed=0):
    rngb = np.random.RandomState(seed)
    return [
        {
            "inputs": rngb.randint(0, V, (B, T)).astype(np.int32),
            "targets": rngb.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(n)
    ]


def _single_device_run(cfg, params, batches, opt):
    def loss_fn(p, b, r):
        return lm_loss(p, b, cfg)

    step = make_train_step(loss_fn, opt)
    s = init_train_state(params, opt, jax.random.PRNGKey(1))
    losses = []
    for b in batches:
        s, m = step(s, b)
        losses.append(float(m["loss"]))
    return s, losses


def _pp_run(cfg, params, batches, opt, *, dp, pp, microbatches):
    mesh = make_mesh(dp=dp, pp=pp)
    stacked = stack_lm_params(params)
    placed = place_pp_lm_params(stacked, mesh)
    step = make_pp_lm_train_step(
        cfg, opt, mesh, stacked, microbatches=microbatches, donate=False
    )
    s = init_train_state(placed, opt, jax.random.PRNGKey(1))
    losses = []
    for b in batches:
        s, m = step(s, b)
        losses.append(float(m["loss"]))
    return s, losses


def test_dp_pp_matches_single_device():
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=4)
    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    batches = _batches(3)

    s0, want = _single_device_run(cfg, params, batches, opt)
    s1, got = _pp_run(cfg, params, batches, opt, dp=2, pp=4, microbatches=4)

    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5
        ),
        jax.device_get(unstack_lm_params(s1.params)),
        jax.device_get(s0.params),
    )


def test_pp_adam_multilayer_stage():
    """2 stages x 2 layers each, adam (exercises sharded opt-state moments),
    single microbatch (pure memory-scaling mode)."""
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=4)
    opt = make_optimizer("adam", 1e-2)
    params = init_lm(jax.random.PRNGKey(2), cfg)
    batches = _batches(2, seed=3)

    _, want = _single_device_run(cfg, params, batches, opt)
    _, got = _pp_run(cfg, params, batches, opt, dp=4, pp=2, microbatches=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pp_rejects_ragged_layers():
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2, embed_size=8)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    try:
        stack_lm_params(params)
    except ValueError as e:
        assert "uniform" in str(e)
    else:
        raise AssertionError("expected ValueError for ragged layer stack")


def test_pp_rejects_dropout():
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2, dropout=0.5)
    opt = make_optimizer("sgd", 0.1)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(dp=4, pp=2)
    stacked = stack_lm_params(params)
    try:
        make_pp_lm_train_step(cfg, opt, mesh, stacked, donate=False)
    except ValueError as e:
        assert "dropout" in str(e)
    else:
        raise AssertionError("expected ValueError for dropout under PP")
