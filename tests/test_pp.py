"""Pipeline parallelism (DP x PP wavefront over stacked layers): exact loss
and parameter parity with the single-device step over several steps."""

import jax
import numpy as np

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_mesh
from lstm_tensorspark_tpu.parallel.pipeline_parallel import (
    make_pp_lm_train_step,
    place_pp_lm_params,
    stack_lm_params,
    unstack_lm_params,
)
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 11, 16, 8, 12


def _batches(n, seed=0):
    rngb = np.random.RandomState(seed)
    return [
        {
            "inputs": rngb.randint(0, V, (B, T)).astype(np.int32),
            "targets": rngb.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(n)
    ]


def _single_device_run(cfg, params, batches, opt):
    def loss_fn(p, b, r):
        return lm_loss(p, b, cfg)

    step = make_train_step(loss_fn, opt)
    s = init_train_state(params, opt, jax.random.PRNGKey(1))
    losses = []
    for b in batches:
        s, m = step(s, b)
        losses.append(float(m["loss"]))
    return s, losses


def _pp_run(cfg, params, batches, opt, *, dp, pp, microbatches, tp=1,
            zero1=False):
    mesh = make_mesh(dp=dp, tp=tp, pp=pp)
    stacked = stack_lm_params(params)
    placed = place_pp_lm_params(stacked, mesh, tp=tp > 1)
    step = make_pp_lm_train_step(
        cfg, opt, mesh, stacked, microbatches=microbatches, donate=False,
        tp=tp > 1, zero1=zero1,
    )
    s = init_train_state(placed, opt, jax.random.PRNGKey(1))
    if zero1:
        from lstm_tensorspark_tpu.parallel.pipeline_parallel import (
            place_pp_zero1_opt_state,
        )

        s = s._replace(opt_state=place_pp_zero1_opt_state(
            s.opt_state, opt, stacked, mesh, tp=tp > 1))
    losses = []
    for b in batches:
        s, m = step(s, b)
        losses.append(float(m["loss"]))
    return s, losses


def test_dp_pp_matches_single_device():
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=4)
    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    batches = _batches(3)

    s0, want = _single_device_run(cfg, params, batches, opt)
    s1, got = _pp_run(cfg, params, batches, opt, dp=2, pp=4, microbatches=4)

    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5
        ),
        jax.device_get(unstack_lm_params(s1.params)),
        jax.device_get(s0.params),
    )


def test_pp_adam_multilayer_stage():
    """2 stages x 2 layers each, adam (exercises sharded opt-state moments),
    single microbatch (pure memory-scaling mode)."""
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=4)
    opt = make_optimizer("adam", 1e-2)
    params = init_lm(jax.random.PRNGKey(2), cfg)
    batches = _batches(2, seed=3)

    _, want = _single_device_run(cfg, params, batches, opt)
    _, got = _pp_run(cfg, params, batches, opt, dp=4, pp=2, microbatches=1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pp_embed_neq_hidden_matches_single_device():
    """embed_size != hidden_size: the zero-padded layer stack must give
    EXACT parity (padded W rows multiply zero lanes; dW_pad = 0)."""
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2, embed_size=8)
    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(4), cfg)
    batches = _batches(3, seed=5)

    s0, want = _single_device_run(cfg, params, batches, opt)
    s1, got = _pp_run(cfg, params, batches, opt, dp=4, pp=2, microbatches=2)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # round-trip recovers the true (unpadded) per-layer shapes and values
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5
        ),
        jax.device_get(unstack_lm_params(s1.params)),
        jax.device_get(s0.params),
    )


def test_pp_tp_composition_matches_single_device():
    """DP x TP x PP (hybrid manual-pipe/auto-model): loss parity over steps,
    with embed != hidden exercising the padded stack under TP too."""
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2, embed_size=8)
    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(6), cfg)
    batches = _batches(3, seed=7)

    _, want = _single_device_run(cfg, params, batches, opt)
    _, got = _pp_run(cfg, params, batches, opt, dp=2, pp=2, tp=2,
                     microbatches=2)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pp_dropout_trains():
    """Inter-layer dropout under PP: runs, loss finite, and the trajectory
    differs from the deterministic run (masks are real). (No learning
    assertion: targets are random and 50% dropout on H=16 makes short-run
    loss decrease unreliable.)"""
    opt = make_optimizer("sgd", 0.3)
    batches = _batches(6, seed=8)
    losses = {}
    for rate in (0.0, 0.5):
        cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2, dropout=rate)
        params = init_lm(jax.random.PRNGKey(9), cfg)
        _, ls = _pp_run(cfg, params, batches, opt, dp=4, pp=2, microbatches=2)
        assert np.isfinite(ls).all()
        losses[rate] = ls
    assert not np.allclose(losses[0.0], losses[0.5])  # masks took effect


def test_pp_sharded_eval_matches_single_device():
    """Sharded PP eval (no host gather) returns the same loss as the
    single-device lm_loss on identical params, and reports global tokens."""
    from lstm_tensorspark_tpu.models import lm_loss
    from lstm_tensorspark_tpu.parallel.pipeline_parallel import (
        make_pp_lm_eval_step,
    )

    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2, embed_size=8)
    params = init_lm(jax.random.PRNGKey(10), cfg)
    mesh = make_mesh(dp=2, tp=2, pp=2)
    stacked = stack_lm_params(params)
    placed = place_pp_lm_params(stacked, mesh, tp=True)
    ev = make_pp_lm_eval_step(cfg, mesh, stacked, microbatches=2, tp=True)
    b = _batches(1, seed=11)[0]
    m = ev(placed, b)
    want, _ = lm_loss(params, b, cfg)
    np.testing.assert_allclose(float(m["loss"]), float(want), rtol=1e-5)
    assert float(m["tokens"]) == B * T


def test_pp_with_pallas_interpret_matches_plain_pp(monkeypatch):
    """--use-pallas composes with --pipeline-stages (VERDICT r2 item 3): the
    stage-interior recurrences run the fused kernel (interpret mode on CPU,
    forced past the platform gate) and must match the plain-scan PP run and
    the single-device run."""
    import functools

    import lstm_tensorspark_tpu.ops.pallas_lstm as pallas_mod

    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=4)
    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(4), cfg)
    batches = _batches(3, seed=5)

    _, want = _single_device_run(cfg, params, batches, opt)
    _, plain = _pp_run(cfg, params, batches, opt, dp=2, pp=4, microbatches=4)

    monkeypatch.setattr(pallas_mod, "supported", lambda *a, **k: True)
    monkeypatch.setattr(
        pallas_mod, "pallas_lstm_scan",
        functools.partial(pallas_mod.pallas_lstm_scan, interpret=True),
    )
    cfg_p = LMConfig(vocab_size=V, hidden_size=H, num_layers=4,
                     use_pallas=True)
    _, got = _pp_run(cfg_p, params, batches, opt, dp=2, pp=4, microbatches=4)

    np.testing.assert_allclose(got, plain, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pp_tp_keeps_pallas_off(monkeypatch):
    """With an auto "model" TP axis the stage interior must NOT take the
    fused path (GSPMD cannot partition pallas_call) even when use_pallas is
    set — the kernel entry would raise if reached (platform-gated off here),
    so plain parity passing proves the gate."""
    import lstm_tensorspark_tpu.ops.pallas_lstm as pallas_mod

    def boom(*a, **k):
        raise AssertionError("pallas dispatch must not be reached under TP")

    cfg_ref = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2, use_pallas=True)
    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(6), cfg)
    batches = _batches(2, seed=7)

    _, want = _single_device_run(cfg_ref, params, batches, opt)
    monkeypatch.setattr(pallas_mod, "supported", boom)
    _, got = _pp_run(cfg, params, batches, opt, dp=2, pp=2, microbatches=2,
                     tp=2)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_zero1_pp_matches_plain_pp_trajectory():
    """ZeRO-1 x PP (VERDICT r3 item 6): stage x data sharded adam moments
    must not change the trajectory — the spec tree only moves WHERE the
    update computes, not what it computes."""
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=4)
    opt = make_optimizer("adam", 3e-3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    batches = _batches(3)

    _, want = _pp_run(cfg, params, batches, opt, dp=2, pp=4, microbatches=4)
    s1, got = _pp_run(cfg, params, batches, opt, dp=2, pp=4, microbatches=4,
                      zero1=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    # the single-device oracle agrees too
    _, ref = _single_device_run(cfg, params, batches, opt)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_zero1_pp_moments_shard_over_pipe_and_data():
    """The memory claim: stacked-layer moment leaves end up sharded over
    BOTH pipe and data (1/(pp*dp) per chip), preserved across steps by the
    out_shardings pin; scalar leaves stay replicated."""
    from jax.sharding import PartitionSpec as P
    from jax.tree_util import GetAttrKey, tree_flatten_with_path

    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=4)
    opt = make_optimizer("adam", 3e-3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    s1, _ = _pp_run(cfg, params, _batches(2), opt, dp=2, pp=4,
                    microbatches=4, zero1=True)
    leaves = tree_flatten_with_path(s1.opt_state)[0]
    layer_mats = [a for path, a in leaves
                  if GetAttrKey("mu") in path and a.ndim == 3]
    assert layer_mats, "expected stacked [L, ., .] moment leaves under .mu"
    for a in layer_mats:
        spec = a.sharding.spec
        assert "pipe" in spec and "data" in spec, spec
        shard = a.addressable_shards[0].data
        assert shard.size * 8 == a.size, (shard.shape, a.shape)
    counts = [a for path, a in leaves if GetAttrKey("count") in path]
    assert counts and all(c.sharding.spec == P() for c in counts)


def test_zero1_pp_tp_triple_composition():
    """zero1 x tp x pp on one mesh: trajectory parity with the
    single-device oracle at dp=2, tp=2, pp=2."""
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
    opt = make_optimizer("adam", 3e-3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    batches = _batches(3)

    _, ref = _single_device_run(cfg, params, batches, opt)
    _, got = _pp_run(cfg, params, batches, opt, dp=2, pp=2, tp=2,
                     microbatches=2, zero1=True)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
