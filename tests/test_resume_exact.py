"""Data-exact resume (VERDICT r2 item 5): a killed-and-resumed run must see
exactly the batches the uninterrupted run would have seen, so the loss
trajectory BIT-matches from the resume point on.

Unit level: every stream fast-forward (`lm_batch_stream`,
`window_index_stream`, `index_groups`) equals dropping the first
``start_step`` items of a fresh stream — including across epoch boundaries,
where the per-epoch shuffle seeds must stay aligned.

E2E level: CLI runs with a mid-budget checkpoint, resumed to the full
budget, compared step-for-step against one uninterrupted run (same jitted
program, same platform ⇒ the comparison is exact equality, not tolerance).
"""

import itertools
import json

import numpy as np

from lstm_tensorspark_tpu.data.batching import (
    example_order,
    index_groups,
    lm_batch_stream,
)


def _take(it, n):
    return list(itertools.islice(it, n))


def test_lm_batch_stream_fast_forward_crosses_epochs():
    tokens = np.arange(100, dtype=np.int32)  # B=4, T=8 -> 3 windows/epoch
    fresh = _take(lm_batch_stream(tokens, 4, 8), 9)
    for start in (1, 3, 4, 7):  # in-epoch, boundary, next-epoch, deep
        resumed = _take(lm_batch_stream(tokens, 4, 8, start_step=start), 2)
        for a, b in zip(resumed, fresh[start : start + 2]):
            np.testing.assert_array_equal(a["inputs"], b["inputs"])
            np.testing.assert_array_equal(a["targets"], b["targets"])


def test_window_index_stream_fast_forward():
    import dataclasses

    from lstm_tensorspark_tpu.data.device_dataset import window_index_stream

    fake = dataclasses.make_dataclass("F", ["n_windows"])(n_windows=5)
    fresh = _take(window_index_stream(fake, 2), 8)
    resumed = _take(window_index_stream(fake, 2, start_step=4), 6)
    assert resumed == fresh[2:]  # start_step=4 = 2 dispatches of k=2


def test_index_groups_fast_forward_crosses_epochs():
    lengths = [3, 7, 2, 9, 5, 4, 8, 1, 6, 2]  # 10 examples, B=3 -> 3/epoch
    order_fn = lambda epoch: example_order(lengths, shuffle_seed=epoch)
    fresh = _take(index_groups(order_fn, 3, 1), 10)
    for start in (1, 2, 3, 4, 8):
        resumed = _take(index_groups(order_fn, 3, 1, start_step=start), 2)
        for a, b in zip(resumed, fresh[start : start + 2]):
            np.testing.assert_array_equal(a, b)


def _losses(jsonl_path):
    out = {}
    for line in open(jsonl_path):
        r = json.loads(line)
        if "loss" in r and "step" in r and r.get("note") is None:
            out[r["step"]] = r["loss"]
    return out


def _run_and_compare(tmp_path, common, *, total=8, cut=4):
    """Uninterrupted run vs checkpoint-at-cut + resume; exact loss equality
    on the post-resume steps."""
    from lstm_tensorspark_tpu.cli import main

    full_jsonl = tmp_path / "full.jsonl"
    assert main(common + [
        "--num-steps", str(total), "--jsonl", str(full_jsonl),
    ]) == 0

    ck = tmp_path / "ck"
    res_jsonl = tmp_path / "resumed.jsonl"
    assert main(common + [
        "--num-steps", str(cut), "--jsonl", str(res_jsonl),
        "--checkpoint-dir", str(ck), "--checkpoint-every", str(cut),
    ]) == 0
    assert main(common + [
        "--num-steps", str(total), "--jsonl", str(res_jsonl),
        "--checkpoint-dir", str(ck), "--resume",
    ]) == 0

    want, got = _losses(full_jsonl), _losses(res_jsonl)
    post = [s for s in want if s > cut]
    assert post, "no post-resume steps logged"
    for s in post:
        assert got[s] == want[s], (
            f"step {s}: resumed loss {got[s]} != uninterrupted {want[s]}"
        )


def test_lm_resume_bitmatch_host_fed(tmp_path):
    _run_and_compare(tmp_path, [
        "--dataset", "ptb_char", "--hidden-units", "16", "--batch-size", "8",
        "--seq-len", "8", "--log-every", "1", "--learning-rate", "0.5",
        "--compute-dtype", "float32",
    ])


def test_lm_resume_bitmatch_device_data(tmp_path):
    """window_index_stream fast-forward: HBM-staged corpus path."""
    _run_and_compare(tmp_path, [
        "--dataset", "ptb_char", "--hidden-units", "16", "--batch-size", "8",
        "--seq-len", "8", "--log-every", "1", "--learning-rate", "0.5",
        "--compute-dtype", "float32", "--device-data",
    ])


def test_classifier_resume_bitmatch(tmp_path):
    """Shuffled-epoch task stream: the resumed run's epoch seed + in-epoch
    offset must reproduce the uninterrupted batch order."""
    _run_and_compare(tmp_path, [
        "--dataset", "imdb", "--hidden-units", "16", "--batch-size", "64",
        "--seq-len", "32", "--log-every", "1", "--learning-rate", "0.1",
        "--compute-dtype", "float32",
    ], total=6, cut=3)


def test_forecaster_resume_bitmatch(tmp_path):
    _run_and_compare(tmp_path, [
        "--dataset", "uci_electricity", "--hidden-units", "16",
        "--batch-size", "32", "--seq-len", "24", "--log-every", "1",
        "--learning-rate", "0.05", "--compute-dtype", "float32",
    ], total=6, cut=3)
