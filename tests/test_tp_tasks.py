"""Tensor parallelism for the non-LM models (classifier, seq2seq) and
dropout under the SP wavefront — VERDICT r1 "widen the parallelism
envelope" items. Parity oracle: the single-device train step."""

import jax
import numpy as np

from lstm_tensorspark_tpu.parallel import make_mesh
from lstm_tensorspark_tpu.parallel.tensor_parallel import (
    classifier_param_specs,
    make_tp_train_step,
    place_params,
    seq2seq_param_specs,
)
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state


def _run(loss_fn, params, batches, opt, *, tp_specs=None, mesh=None):
    if tp_specs is None:
        step = make_train_step(loss_fn, opt)
        s = init_train_state(params, opt, jax.random.PRNGKey(1))
    else:
        step = make_tp_train_step(loss_fn, opt, mesh, params,
                                  param_specs=tp_specs, donate=False)
        placed = place_params(params, tp_specs, mesh)
        s = init_train_state(placed, opt, jax.random.PRNGKey(1))
    losses = []
    for b in batches:
        s, m = step(s, b)
        losses.append(float(m["loss"]))
    return s, losses


def test_tp_classifier_matches_single_device():
    from lstm_tensorspark_tpu.models import (
        ClassifierConfig, classifier_loss, init_classifier,
    )

    V, H, B, T = 13, 16, 8, 12
    cfg = ClassifierConfig(vocab_size=V, hidden_size=H, num_layers=2)
    params = init_classifier(jax.random.PRNGKey(0), cfg)
    opt = make_optimizer("sgd", 0.3)
    rng = np.random.RandomState(0)
    batches = [
        {
            "tokens": rng.randint(0, V, (B, T)).astype(np.int32),
            "lengths": rng.randint(3, T + 1, (B,)).astype(np.int32),
            "labels": rng.randint(0, 2, (B,)).astype(np.int32),
            "valid": np.ones((B,), np.float32),
        }
        for _ in range(3)
    ]

    def loss_fn(p, b, r):
        return classifier_loss(p, b, cfg)

    mesh = make_mesh(dp=4, tp=2)
    s0, want = _run(loss_fn, params, batches, opt)
    s1, got = _run(loss_fn, params, batches, opt,
                   tp_specs=classifier_param_specs(params), mesh=mesh)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-5
        ),
        jax.device_get(s1.params), jax.device_get(s0.params),
    )


def test_tp_seq2seq_matches_single_device():
    from lstm_tensorspark_tpu.models import (
        Seq2SeqConfig, init_seq2seq, seq2seq_loss,
    )

    F, H, B, T, HOR = 5, 16, 8, 12, 4
    cfg = Seq2SeqConfig(num_features=F, hidden_size=H, num_layers=2,
                        horizon=HOR)
    params = init_seq2seq(jax.random.PRNGKey(2), cfg)
    opt = make_optimizer("adam", 1e-2)
    rng = np.random.RandomState(1)
    batches = [
        {
            "context": rng.randn(B, T, F).astype(np.float32),
            "targets": rng.randn(B, HOR, F).astype(np.float32),
        }
        for _ in range(3)
    ]

    def loss_fn(p, b, r):
        return seq2seq_loss(p, b, cfg)

    mesh = make_mesh(dp=2, tp=4)
    _, want = _run(loss_fn, params, batches, opt)
    _, got = _run(loss_fn, params, batches, opt,
                  tp_specs=seq2seq_param_specs(params), mesh=mesh)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_sp_dropout_runs():
    """Dropout under the SP wavefront: finite losses, trajectory differs
    from deterministic (per-shard masks are live)."""
    from lstm_tensorspark_tpu.models import LMConfig, init_lm
    from lstm_tensorspark_tpu.parallel.train_step import (
        make_sharded_lm_train_step,
    )
    from lstm_tensorspark_tpu.parallel.tensor_parallel import place_lm_params

    V, H, B, T = 11, 16, 8, 16
    rng = np.random.RandomState(2)
    batches = [
        {
            "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
            "targets": rng.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(4)
    ]
    opt = make_optimizer("sgd", 0.3)
    losses = {}
    for rate in (0.0, 0.5):
        cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2, dropout=rate)
        params = init_lm(jax.random.PRNGKey(3), cfg)
        mesh = make_mesh(dp=2, tp=2, sp=2)
        step = make_sharded_lm_train_step(cfg, opt, mesh, params,
                                          microbatches=2, donate=False)
        s = init_train_state(place_lm_params(params, mesh), opt,
                             jax.random.PRNGKey(4))
        ls = []
        for b in batches:
            s, m = step(s, b)
            ls.append(float(m["loss"]))
        assert np.isfinite(ls).all()
        losses[rate] = ls
    assert not np.allclose(losses[0.0], losses[0.5])


def test_sharded_eval_matches_single_device():
    """TP/SP sharded eval (no host gather): loss parity with lm_loss and a
    global token count for exact token weighting."""
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
    from lstm_tensorspark_tpu.parallel.tensor_parallel import place_lm_params
    from lstm_tensorspark_tpu.parallel.train_step import (
        make_sharded_lm_eval_step,
    )

    V, H, B, T = 11, 16, 8, 16
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
    params = init_lm(jax.random.PRNGKey(5), cfg)
    mesh = make_mesh(dp=2, tp=2, sp=2)
    placed = place_lm_params(params, mesh)
    ev = make_sharded_lm_eval_step(cfg, mesh, params, microbatches=2)
    rng = np.random.RandomState(6)
    b = {
        "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
        "targets": rng.randint(0, V, (B, T)).astype(np.int32),
    }
    m = ev(placed, b)
    want, _ = lm_loss(params, b, cfg)
    np.testing.assert_allclose(float(m["loss"]), float(want), rtol=1e-5)
    assert float(m["tokens"]) == B * T


def test_tp_classifier_eval_on_sharded_params():
    """make_tp_eval_step: eval metrics computed on the device-resident
    TP-sharded params match the plain single-device eval (VERDICT r2
    weak #6 — no host gather)."""
    from lstm_tensorspark_tpu.models import (
        ClassifierConfig, classifier_loss, init_classifier,
    )
    from lstm_tensorspark_tpu.parallel.tensor_parallel import make_tp_eval_step

    V, H, B, T = 13, 16, 8, 12
    cfg = ClassifierConfig(vocab_size=V, hidden_size=H, num_layers=1)
    params = init_classifier(jax.random.PRNGKey(7), cfg)
    mesh = make_mesh(dp=4, tp=2)
    specs = classifier_param_specs(params)
    placed = place_params(params, specs, mesh)
    ev = make_tp_eval_step(lambda p, b: classifier_loss(p, b, cfg)[1],
                           mesh, specs)
    rng = np.random.RandomState(8)
    b = {
        "tokens": rng.randint(0, V, (B, T)).astype(np.int32),
        "lengths": rng.randint(3, T + 1, (B,)).astype(np.int32),
        "labels": rng.randint(0, 2, (B,)).astype(np.int32),
        "valid": np.ones((B,), np.float32),
    }
    got = ev(placed, b)
    want = classifier_loss(params, b, cfg)[1]
    np.testing.assert_allclose(float(got["loss"]), float(want["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(got["accuracy"]),
                               float(want["accuracy"]), rtol=1e-6)


def test_tp_seq2seq_eval_on_sharded_params():
    """Free-running forecast on TP-sharded params matches single-device."""
    from lstm_tensorspark_tpu.models import (
        Seq2SeqConfig, forecast, init_seq2seq,
    )
    from lstm_tensorspark_tpu.parallel.tensor_parallel import make_tp_eval_step

    F, H, B, T = 5, 16, 8, 12
    cfg = Seq2SeqConfig(num_features=F, hidden_size=H, num_layers=2, horizon=4)
    params = init_seq2seq(jax.random.PRNGKey(9), cfg)
    mesh = make_mesh(dp=2, tp=4)
    specs = seq2seq_param_specs(params)
    placed = place_params(params, specs, mesh)
    fc = make_tp_eval_step(lambda p, ctx: forecast(p, ctx, cfg), mesh, specs)
    ctx = np.random.RandomState(10).randn(B, T, F).astype(np.float32)
    got = np.asarray(fc(placed, ctx))
    want = np.asarray(forecast(params, ctx, cfg))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
