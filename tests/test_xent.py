"""Vocab-chunked cross-entropy (ops/xent.py): exactness vs the plain
logsumexp loss — values AND gradients, padded-V and tied-head cases."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.ops.xent import chunked_xent_mean

B, T, H, V = 4, 6, 16, 37  # V deliberately off the chunk grid


def _ref_loss(ys, kernel, bias, targets):
    logits = (
        jnp.dot(ys, kernel, preferred_element_type=jnp.float32) + bias
    ).astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt)


def _setup(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    ys = jax.random.normal(ks[0], (B, T, H))
    kernel = jax.random.normal(ks[1], (H, V)) * 0.3
    bias = jax.random.normal(ks[2], (V,)) * 0.1
    targets = jax.random.randint(ks[3], (B, T), 0, V)
    return ys, kernel, bias, targets


def test_value_matches_reference():
    ys, kernel, bias, targets = _setup()
    for chunk in (8, 16, 64):  # multiple tiles / pad-only / single tile
        got = chunked_xent_mean(ys, kernel, bias, targets, chunk)
        np.testing.assert_allclose(
            float(got), float(_ref_loss(ys, kernel, bias, targets)),
            rtol=1e-6,
        )


def test_grads_match_reference():
    ys, kernel, bias, targets = _setup(seed=1)
    g1 = jax.grad(
        lambda y, k, b: chunked_xent_mean(y, k, b, targets, 8),
        argnums=(0, 1, 2),
    )(ys, kernel, bias)
    g2 = jax.grad(
        lambda y, k, b: _ref_loss(y, k, b, targets), argnums=(0, 1, 2)
    )(ys, kernel, bias)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
        g1, g2,
    )


def test_under_jit_and_value_and_grad():
    ys, kernel, bias, targets = _setup(seed=2)
    f = jax.jit(jax.value_and_grad(
        lambda y, k, b, t: chunked_xent_mean(y, k, b, t, 16),
        argnums=(0, 1, 2),
    ))
    v, g = f(ys, kernel, bias, targets)
    np.testing.assert_allclose(
        float(v), float(_ref_loss(ys, kernel, bias, targets)), rtol=1e-6
    )
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))


def test_lm_loss_big_v_parity(monkeypatch):
    """lm_loss's big-V path (auto-selected above _CHUNKED_XENT_MIN_V) must
    match a hand-computed plain loss on the same params — including
    gradients through the whole model. The threshold is lowered for the
    test so the parity check stays cheap (the real threshold targets
    vocabularies whose logits would not fit HBM)."""
    import lstm_tensorspark_tpu.models.lstm_lm as lm_mod
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_forward, lm_loss

    monkeypatch.setattr(lm_mod, "_CHUNKED_XENT_MIN_V", 4096)
    V_big = 4109
    cfg = LMConfig(vocab_size=V_big, hidden_size=16, num_layers=1)
    params = init_lm(jax.random.PRNGKey(3), cfg)
    data = jax.random.randint(jax.random.PRNGKey(4), (B, T + 1), 0, V_big)
    batch = {"inputs": data[:, :-1], "targets": data[:, 1:]}

    def plain(p):
        logits, _ = lm_forward(p, batch["inputs"], cfg)
        lg = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(lg, axis=-1)
        tgt = jnp.take_along_axis(lg, batch["targets"][..., None],
                                  axis=-1)[..., 0]
        return jnp.mean(lse - tgt)

    def chunked(p):
        return lm_loss(p, batch, cfg)[0]

    np.testing.assert_allclose(float(chunked(params)), float(plain(params)),
                               rtol=1e-6)
    g1 = jax.grad(chunked)(params)
    g2 = jax.grad(plain)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-7),
        g1, g2,
    )
