"""Windowed multi-token decode (serve/engine.py `decode_window` +
serve/batcher.py adaptive windowing with async readback).

The contract under test:

- greedy output through the windowed path is TOKEN-IDENTICAL to the K=1
  path and to `models/generate.py`, across window boundaries and when EOS
  lands inside a window;
- the compile lattice stays bounded: at most ONE XLA compile per
  ("decode_window", batch-bucket, K, sampling-config), proved by replay;
- dispatch-ahead pipelining (window i+1 dispatched from window i's device
  handles before window i is fetched) changes nothing observable;
- a request submitted while a window is in flight is admitted within one
  scheduler iteration (the continuous-batching admission property).
"""

import threading

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.serve import (
    PAD_TOKEN,
    Batcher,
    Request,
    ServeEngine,
    ServeServer,
    InprocessClient,
)

_CFG = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)


def _params():
    return init_lm(jax.random.PRNGKey(11), _CFG)


def _engine(params, **kw):
    kw.setdefault("num_slots", 8)
    kw.setdefault("prefill_buckets", (4, 8))
    kw.setdefault("batch_buckets", (1, 2, 4))
    return ServeEngine(params, _CFG, **kw)


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 37, size=n).astype(np.int32)


@pytest.fixture(scope="module")
def params():
    return _params()


@pytest.fixture(scope="module")
def windowed(params):
    """One module-scoped windowed server (ladder 1/4/8 — the default)."""
    server = ServeServer(_engine(params), max_active=4, queue_size=16)
    server.start()
    yield server
    server.stop()


# ---- greedy parity across window boundaries ------------------------------


def test_windowed_greedy_matches_k1_and_generate(params, windowed):
    """max_new_tokens values straddling the ladder (10 = prefill+8+1,
    13 = prefill+8+4 — both cross window boundaries mid-stream) must be
    token-identical to the per-token batcher AND to models/generate.py."""
    prompts = [_prompt(3, 1), _prompt(6, 2)]
    k1 = ServeServer(_engine(params), max_active=4, queue_size=16,
                     window_ladder=(1,))
    client_w = InprocessClient(windowed)
    with k1:
        client_1 = InprocessClient(k1)
        for n_new in (10, 13):
            gen = make_generate_fn(_CFG, max_new_tokens=n_new, greedy=True)
            for p in prompts:
                ref = np.asarray(
                    gen(params, p[None, :], jax.random.PRNGKey(0))
                )[0, p.size:]
                got_w = client_w.generate(p, max_new_tokens=n_new)
                got_1 = client_1.generate(p, max_new_tokens=n_new)
                np.testing.assert_array_equal(np.asarray(got_w), ref)
                np.testing.assert_array_equal(np.asarray(got_1), ref)
    # the windowed server actually used windows (not a silent K=1 run)
    dispatched = windowed.batcher.windows_dispatched
    assert any(k > 1 for k in dispatched), dispatched


def test_concurrent_windowed_sessions_match_generate(params, windowed):
    prompts = [_prompt(2, 3), _prompt(7, 5)]
    n_new = 11
    client = InprocessClient(windowed)
    got = [None] * len(prompts)

    def run_one(i):
        got[i] = client.generate(prompts[i], max_new_tokens=n_new)

    threads = [threading.Thread(target=run_one, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    gen = make_generate_fn(_CFG, max_new_tokens=n_new, greedy=True)
    for i, p in enumerate(prompts):
        ref = np.asarray(gen(params, p[None, :], jax.random.PRNGKey(0)))[
            0, p.size:]
        np.testing.assert_array_equal(np.asarray(got[i], np.int32), ref)


# ---- EOS inside a window -------------------------------------------------


def test_eos_inside_window_stops_exactly(params, windowed):
    """Pick an EOS id that the greedy stream emits mid-window: the
    windowed request must stop AT that token (on-device latch → PAD
    padding afterwards), exactly like the K=1 path."""
    p = _prompt(4, 6)
    client = InprocessClient(windowed)
    probe = client.generate(p, max_new_tokens=12)
    assert len(probe) == 12
    # an id first emitted strictly inside the first K=8 window
    eos, first_idx = None, None
    for idx in range(2, 7):
        if probe[idx] not in probe[:idx]:
            eos, first_idx = probe[idx], idx
            break
    if eos is None:
        pytest.skip("greedy stream has no unique mid-window token")
    again = client.generate(p, max_new_tokens=12, eos_id=int(eos))
    # stops AT the eos token — identical to truncating the eos-free
    # stream there, which is exactly what the K=1 path does (greedy
    # windowed/K=1 parity itself is test_windowed_greedy_matches_*)
    assert again == probe[: first_idx + 1]


def test_window_program_pads_after_eos(params):
    """Engine-level: the rows of a fetched window are PAD_TOKEN after the
    EOS position, and a pipelined follow-up window (dispatched BEFORE the
    fetch) leaves the latched row frozen."""
    engine = _engine(params)
    slot, _ = engine.cache.acquire("s")
    first = engine.prefill([(slot, True, _prompt(3, 7))])
    # probe the continuation to find a mid-window token to use as EOS
    probe_win = engine.decode_window([slot], [int(first[0])], [8], window=8)
    stream = [int(t) for t in ServeEngine.fetch_window(probe_win)[0]]
    eos = stream[2]
    first_idx = stream.index(eos)

    # fresh session, same engine (the compiled programs replay): rerun
    # the same continuation WITH the eos armed
    slot2, _ = engine.cache.acquire("s2")
    f2 = engine.prefill([(slot2, True, _prompt(3, 7))])
    win = engine.decode_window([slot2], [int(f2[0])], [8],
                               eos_ids=[eos], window=8)
    nxt = engine.decode_window_next(win)  # dispatch-ahead, pre-fetch
    row = ServeEngine.fetch_window(win)[0]
    assert [int(t) for t in row[: first_idx + 1]] == stream[: first_idx + 1]
    assert all(int(t) == PAD_TOKEN for t in row[first_idx + 1:])
    # the latched row stays frozen through the pipelined window: all PAD
    assert all(int(t) == PAD_TOKEN for t in ServeEngine.fetch_window(nxt)[0])


# ---- bounded compile lattice ---------------------------------------------


def test_window_compile_lattice_bounded(params):
    """≤1 compile per ("decode_window", batch-bucket, K, sampling) —
    asserted via trace-time compile_counts, then re-proved by replaying
    the same workload shape (zero new compiles). Driven through the
    Batcher directly (submit-then-drain) so admission batching — and
    therefore the program shapes — is deterministic, unlike racing
    client threads."""
    engine = _engine(params)
    batcher = Batcher(engine, max_active=4, queue_size=16)

    def workload(seed):
        reqs = [Request(_prompt(3 + i, seed + i), 12) for i in range(3)]
        for r in reqs:
            batcher.submit(r)
        batcher.drain()
        assert all(r.error is None and len(r.tokens) == 12 for r in reqs)

    workload(20)
    counts = dict(engine.compile_counts)
    assert counts and all(v == 1 for v in counts.values()), counts
    wkeys = [k for k in counts if k[0] == "decode_window"]
    assert wkeys, counts  # the windowed path actually compiled windows
    for k in wkeys:
        assert k[1] in engine.batch_buckets  # batch bucket
        assert k[2] in batcher.window_ladder  # K is a ladder rung
    # ladder lattice bound: |batch buckets| x |ladder|
    assert len(wkeys) <= (len(engine.batch_buckets)
                          * len(batcher.window_ladder))
    workload(50)  # same shapes again → zero new compiles
    assert dict(engine.compile_counts) == counts


def test_warmup_precompiles_window_lattice(params):
    engine = _engine(params, batch_buckets=(1, 2))
    n = engine.warmup(prompt_lens=(3,), windows=(1, 8))
    counts = dict(engine.compile_counts)
    assert all(v == 1 for v in counts.values())
    # every rung gets a window program (K=1 included: the pipelined tail
    # dispatches K=1 windows)
    assert engine.num_compiles("decode_window") == 2 * 2  # buckets x ladder
    assert engine.warmup(prompt_lens=(3,), windows=(1, 8)) == n
    assert dict(engine.compile_counts) == counts


# ---- admission latency under windowing -----------------------------------


def test_mid_window_submit_admitted_within_one_iteration(params):
    """A request submitted while a decode window is in flight must be
    admitted (prefilled, first token produced) by the NEXT scheduler
    iteration — the continuous-batching admission property survives
    windowing because the window ladder drops to K=1 while the queue is
    non-empty."""
    engine = _engine(params)
    batcher = Batcher(engine, max_active=4, queue_size=8)
    long_req = Request(_prompt(4, 30), 24)
    batcher.submit(long_req)
    batcher.step()  # admit + dispatch the first window
    assert batcher._pending is not None  # a window IS in flight
    late = Request(_prompt(2, 31), 2)
    batcher.submit(late)
    batcher.step()  # ONE iteration: resolve the window AND admit `late`
    assert late.t_first_token is not None and len(late.tokens) >= 1
    batcher.drain()
    assert late.error is None and long_req.error is None
    assert len(long_req.tokens) == 24
    # while the queue was non-empty / rows mixed, ladder fell back — but
    # steady-state did pipeline at least one window ahead
    assert batcher.windows_pipelined >= 1
    assert engine.cache.stats()["live_sessions"] == 0


def test_cache_generation_counts_window_grain(params):
    """The cache advances once per PROGRAM (window), not per token:
    tokens_generated / generation grows with the window size."""
    engine = _engine(params)
    batcher = Batcher(engine, max_active=2, queue_size=4)
    req = Request(_prompt(3, 40), 17)
    batcher.submit(req)
    batcher.drain()
    gen = engine.cache.stats()["generation"]
    assert len(req.tokens) == 17
    # 1 prefill + windows(8+8+... / ladder tail) — far fewer programs
    # than 1 + 16 per-token decodes
    assert gen < 1 + 16, gen
