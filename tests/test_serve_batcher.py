"""Batcher/scheduler tests (serve/batcher.py + serve/engine.py): bucket
padding, the bounded-recompile contract (at most ONE XLA compile per
(phase, bucket) even under mixed prompt lengths), backpressure, and
continuous-batching fairness.

Most tests share one module-scoped engine (each builds its own Batcher —
batchers are free) so the file pays each (phase, bucket) compile once;
the shared-engine compile-count assertions stay valid precisely BECAUSE
of the contract under test: replaying a shape never recompiles it. Tests
that assert exact fresh-engine counts build their own small engine."""

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.serve import (
    Batcher,
    QueueFullError,
    Request,
    SamplingParams,
    ServeEngine,
)

_CFG = LMConfig(vocab_size=29, hidden_size=12, num_layers=1)


def _make_engine(**kw):
    params = init_lm(jax.random.PRNGKey(1), _CFG)
    kw.setdefault("num_slots", 16)
    kw.setdefault("prefill_buckets", (4, 8, 16))
    kw.setdefault("batch_buckets", (1, 2, 4))
    return ServeEngine(params, _CFG, **kw)


@pytest.fixture(scope="module")
def engine():
    return _make_engine()


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 29, size=n).astype(np.int32)


# ---- bucket padding ------------------------------------------------------


def test_prefill_pads_to_length_bucket(engine):
    # runs FIRST in the file (tests are order-stable: no pytest-randomly
    # in tier-1), so the engine's compile log is still empty
    scratch = engine.cache.scratch_slot
    engine.prefill([(scratch, True, _prompt(3))])   # 3 → bucket 4
    engine.prefill([(scratch, True, _prompt(11))])  # 11 → bucket 16
    keys = set(engine.compile_counts)
    assert ("prefill", 1, 4, SamplingParams(greedy=True).key()) in keys
    assert ("prefill", 1, 16, SamplingParams(greedy=True).key()) in keys
    # no compile for the skipped middle bucket
    assert not any(k[0] == "prefill" and k[2] == 8 for k in keys)


def test_batch_pads_to_batch_bucket(engine):
    scratch = engine.cache.scratch_slot
    items = [(scratch, True, _prompt(2, s)) for s in range(3)]
    out = engine.prefill(items)  # 3 rows → batch bucket 4
    assert out.shape == (3,)  # padding rows are stripped from the result
    assert any(k[0] == "prefill" and k[1] == 4 for k in engine.compile_counts)
    nxt = engine.decode([scratch] * 3, [1, 2, 3])
    assert nxt.shape == (3,)
    assert any(k[0] == "decode" and k[1] == 4 for k in engine.compile_counts)


def test_prompt_longer_than_largest_bucket_rejected(engine):
    batcher = Batcher(engine, max_active=4, queue_size=4)
    with pytest.raises(ValueError):
        batcher.submit(Request(_prompt(17), 2))  # > max bucket 16


# ---- bounded recompiles --------------------------------------------------


def test_one_compile_per_bucket_and_phase_under_mixed_lengths(engine):
    """The ISSUE acceptance bound: a run with mixed prompt lengths triggers
    at most one XLA compile per (bucket, phase) — asserted via trace-time
    counters, then re-proved by replaying the same workload shape."""
    batcher = Batcher(engine, max_active=4, queue_size=32)
    lengths = [2, 3, 4, 5, 7, 8, 9, 13, 16, 1, 6, 11]
    for i, t in enumerate(lengths):
        batcher.submit(Request(_prompt(t, seed=i), 3))
    batcher.drain()

    counts = dict(engine.compile_counts)
    assert counts, "no compiles recorded"
    assert all(v == 1 for v in counts.values()), counts
    # phases compile per-bucket, not per-request: far fewer programs than
    # requests
    assert engine.num_compiles("prefill") <= 3 * 3  # |len buckets| x |batch|
    assert engine.num_compiles("decode") <= 3       # |batch buckets|

    before = dict(counts)
    for i, t in enumerate(lengths):  # same shapes again → zero new compiles
        batcher.submit(Request(_prompt(t, seed=100 + i), 3))
    batcher.drain()
    assert dict(engine.compile_counts) == before


def test_warmup_precompiles_the_lattice():
    own = _make_engine(prefill_buckets=(4,), batch_buckets=(1, 2))
    n_programs = own.warmup(prompt_lens=(3,))
    counts = dict(own.compile_counts)
    assert all(v == 1 for v in counts.values())
    # every batch bucket compiled for decode and for the length bucket
    assert own.num_compiles("decode") == 2
    assert own.num_compiles("prefill") == 2
    # replay: warmup again → nothing new
    assert own.warmup(prompt_lens=(3,)) == n_programs
    assert dict(own.compile_counts) == counts


# ---- backpressure / admission control -----------------------------------


def test_bounded_queue_backpressure(engine):
    batcher = Batcher(engine, max_active=2, queue_size=2)
    batcher.submit(Request(_prompt(2), 2))
    batcher.submit(Request(_prompt(2), 2))
    with pytest.raises(QueueFullError):
        batcher.submit(Request(_prompt(2), 2))
    assert batcher.rejected == 1
    batcher.drain()  # queue drains; admission resumes
    batcher.submit(Request(_prompt(2), 2))
    batcher.drain()
    assert batcher.completed == 3


def test_max_active_bounds_admission(engine):
    batcher = Batcher(engine, max_active=2, queue_size=16)
    reqs = [Request(_prompt(2, s), 6) for s in range(5)]
    for r in reqs:
        batcher.submit(r)
    batcher.step()
    stats = batcher.stats()
    assert stats["active"] == 2 and stats["queued"] == 3
    batcher.drain()
    assert batcher.completed == 5


def test_max_active_cannot_exceed_cache_slots():
    own = _make_engine(num_slots=2)
    with pytest.raises(ValueError):
        Batcher(own, max_active=3)


# ---- fairness / continuous batching -------------------------------------


def test_every_active_session_advances_each_step(engine):
    # window_ladder=(1,) pins the per-token path: this test asserts the
    # EXACT one-token-per-step cadence (the windowed cadence — up to K
    # tokens per iteration, delivered a step later — is covered by
    # tests/test_serve_window.py)
    batcher = Batcher(engine, max_active=4, queue_size=8, window_ladder=(1,))
    a = Request(_prompt(2, 0), 6)
    b = Request(_prompt(3, 1), 6)
    batcher.submit(a)
    batcher.submit(b)
    batcher.step()  # admission+prefill gives each its first token, then +1
    assert len(a.tokens) == len(b.tokens) == 2
    batcher.step()
    assert len(a.tokens) == len(b.tokens) == 3
    batcher.drain()


def test_late_short_request_finishes_before_early_long_one(engine):
    """The continuous-batching property: prefills join between decode
    steps, so a short request submitted late completes while an earlier
    long session is still decoding."""
    batcher = Batcher(engine, max_active=4, queue_size=8)
    long_req = Request(_prompt(4, 0), 12)
    batcher.submit(long_req)
    batcher.step()
    batcher.step()  # long session mid-flight
    short = Request(_prompt(2, 1), 2)
    batcher.submit(short)
    steps = 0
    while not short.done.is_set() and steps < 10:
        batcher.step()
        steps += 1
    assert short.done.is_set() and short.error is None
    assert not long_req.done.is_set()  # still decoding
    batcher.drain()
    assert long_req.done.is_set() and len(long_req.tokens) == 12


def test_eos_stops_early(engine):
    batcher = Batcher(engine, max_active=2, queue_size=4)
    probe = Request(_prompt(3, 2), 6)
    batcher.submit(probe)
    batcher.drain()
    eos = probe.tokens[2]
    again = Request(_prompt(3, 2), 6, eos_id=eos)
    batcher.submit(again)
    batcher.drain()
    assert again.tokens == probe.tokens[:3]  # stops AT the eos token


def test_sampling_config_cap_bounds_compiles():
    """Sampling params are compile keys and client-controlled at the HTTP
    boundary: the engine refuses configs beyond max_sampling_configs
    instead of compile-thrashing."""
    own = _make_engine(max_sampling_configs=1, prefill_buckets=(4,),
                       batch_buckets=(1,))
    scratch = own.cache.scratch_slot
    own.prefill([(scratch, True, _prompt(2))])  # greedy takes the one slot
    with pytest.raises(ValueError, match="sampling configs"):
        own.decode([scratch], [0], SamplingParams(temperature=0.5))
    # the refusal happens before any trace: nothing new compiled
    assert own.num_compiles() == 1


def test_mixed_sampling_configs_batch_separately(engine):
    batcher = Batcher(engine, max_active=4, queue_size=8)
    greedy = Request(_prompt(2, 3), 3)
    sampled = Request(_prompt(2, 4), 3,
                      sampling=SamplingParams(temperature=0.7, top_k=5))
    batcher.submit(greedy)
    batcher.submit(sampled)
    batcher.drain()
    assert greedy.error is None and sampled.error is None
    assert len(greedy.tokens) == len(sampled.tokens) == 3
    skeys = {k[-1] for k in engine.compile_counts}
    assert len(skeys) == 2  # two sampling configs → two program families


def test_concurrent_requests_on_one_session_rejected(engine):
    """Two in-flight requests on one session_id would share a cache slot
    and corrupt each other's carries — the newcomer must fail loudly."""
    batcher = Batcher(engine, max_active=4, queue_size=8)
    first = Request(_prompt(2, 0), 6, keep_session=True)
    batcher.submit(first)
    batcher.step()  # first is now active
    # first's sid is assigned at admission; read it off the active session
    dup = Request(_prompt(2, 1), 2, session_id=batcher._active[0].sid)
    batcher.submit(dup)
    batcher.drain()
    assert dup.error is not None and "busy" in dup.error
    assert first.error is None and len(first.tokens) == 6
    engine.cache.release(first.session_id)


def test_cancelled_requests_dropped_and_freed(engine):
    """A client that times out sets .cancelled: queued requests drop at
    admission, active ones retire mid-decode and free their slot."""
    batcher = Batcher(engine, max_active=2, queue_size=8)
    active_req = Request(_prompt(2, 0), 8)
    queued_req = Request(_prompt(2, 1), 8)
    blocker = Request(_prompt(2, 2), 8)
    batcher.submit(active_req)
    batcher.submit(blocker)
    batcher.submit(queued_req)  # stays queued: max_active=2
    batcher.step()
    assert batcher.stats()["active"] == 2 and batcher.stats()["queued"] == 1
    active_req.cancelled = True
    queued_req.cancelled = True
    batcher.drain()
    assert active_req.error == "cancelled mid-decode"
    assert queued_req.error == "cancelled before admission"
    assert len(active_req.tokens) < 8  # stopped early, slot freed
    assert blocker.error is None and len(blocker.tokens) == 8
    assert engine.cache.stats()["live_sessions"] == 0
