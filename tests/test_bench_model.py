"""CPU-testable pieces of the benchmark harness (bench.py): the
strategy-aware implementation bound must track the runtime's own backward
gate for every table config."""

import sys
import os

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_impl_bound_tracks_runtime_strategy_per_config():
    """impl_bwd_strategy comes from chosen_bwd_strategy at each config's
    layer-0 shape; the serialized pass count is layers x dirs x (1 + the
    strategy's in-chain multiplier). Pin today's five configs so a cost-
    model change that silently flips a plan shows up here, not only in a
    stale BENCH_TABLE."""
    import bench

    rl = {"chain_sec": 1e-4, "chain_flops": 1e9}
    rec = {"train_flops_step": 1e10}
    want = {
        "ptb_char": ("resident", 2),       # L=1, uni, stored-z bwd
        # L=1, bi: BOTH directions advance in the stacked-direction kernel
        # (ops/pallas_bilstm.py) — one serialized residentx chain
        "imdb_bilstm": ("residentx", 3),
        # r4 chunk-flexible planning (pallas_lstm._plan_bwd): resident is
        # tried at chunks 8/4/2/1 before falling through to tiled, and the
        # bf16 residual streams (_rbytes) halve the streamed-block VMEM, so
        # H=650/1024 (padded 768/1024) now fit U^T resident where they
        # previously spilled to tiled. Hardware caveat: at H=1024 U^T alone
        # is ~8.4 MiB bf16 against the 12 MiB budget — tests_tpu validates
        # the plan compiles and wins on real silicon (chip_recovery queue).
        "wikitext2": ("resident", 4),      # L=2, uni, U^T resident (r4 flip)
        "uci_seq2seq": ("resident", 4),    # L=2 (dU hoist refit resident)
        "wikitext103": ("resident", 8),    # L=4, uni, U^T resident (r4 flip)
    }
    for name, (strategy, passes) in want.items():
        out = bench._impl_bound(name, dict(rl), rec, measured=1e-3)
        assert out["impl_bwd_strategy"] == strategy, (name, out)
        assert out["impl_serial_passes"] == passes, (name, out)
        # bound = passes * chain + parallel remainder, vs UNROUNDED measured
        parallel = max(1e10 - passes * 1e9, 0.0) / (bench.PEAK_TFLOPS * 1e12)
        assert out["impl_bound_sec_per_step"] == pytest.approx(
            passes * 1e-4 + parallel, abs=1.5e-6)


def test_impl_bound_bidir_fuse_lever(monkeypatch):
    """LSTM_TSP_NO_BIDIR_FUSE=1 must restore the two-serialized-scans
    model for the classifier — the bound follows the SAME lever the
    runtime dispatch honors, so A/B numbers get matching bounds."""
    import bench

    monkeypatch.setenv("LSTM_TSP_NO_BIDIR_FUSE", "1")
    out = bench._impl_bound(
        "imdb_bilstm", {"chain_sec": 1e-4, "chain_flops": 1e9},
        {"train_flops_step": 1e10}, measured=1e-3)
    assert out["impl_bwd_strategy"] == "residentx"
    assert out["impl_serial_passes"] == 6


def test_impl_bound_heterogeneous_scans_report_mixed(monkeypatch):
    """ADVICE r3: a config whose scans plan DIFFERENT strategies must not
    inherit the layer-0 label. A long-context seq2seq (encoder T >= the
    fusedx threshold, horizon 24) plans residentx encoders + resident
    decoders: the label goes 'mixed', per-strategy counts are published,
    and the serialized steps weight each scan by its own length."""
    import bench

    cfgs = dict(bench.CONFIGS)
    cfgs["long_seq2seq"] = dict(kind="seq2seq", F=370, H=256, L=2, B=64,
                                T=300, horizon=24)
    monkeypatch.setattr(bench, "CONFIGS", cfgs)
    out = bench._impl_bound(
        "long_seq2seq", {"chain_sec": 1e-4, "chain_flops": 1e9},
        {"train_flops_step": 1e10}, measured=1e-3)
    assert out["impl_bwd_strategy"] == "mixed"
    assert out["impl_bwd_strategies"] == {"residentx": 2, "resident": 2}
    # 2 encoder scans: 300*(1+2); 2 decoder scans: 24*(1+1)
    assert out["impl_serial_steps"] == 2 * 300 * 3 + 2 * 24 * 2
    assert out["impl_serial_passes"] == pytest.approx(1896 / 324, abs=1e-4)


def test_fail_json_contract_matches_success_metric():
    """The wedge/liveness failure line must carry the SAME metric/unit
    strings as the success line so the driver records a 0-value datapoint
    of the tracked series, not an unknown metric."""
    import json
    import subprocess
    import sys as _sys

    out = subprocess.run(
        [_sys.executable, "-c",
         "import bench, os\n"
         "os._exit = lambda c: (_ for _ in ()).throw(SystemExit(c))\n"
         "try:\n"
         "    bench._fail_json('test-error')\n"
         "except SystemExit:\n"
         "    pass\n"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["metric"] == "ptb_char_lstm_train_seq_per_sec_per_chip"
    assert line["unit"] == "seq/sec"
    assert line["value"] == 0.0
    assert "test-error" in line["error"]
