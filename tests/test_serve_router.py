"""Data-parallel replicated serving (serve/router.py): session→replica
affinity stickiness, the global admission bound, replica-death handling
(queued-request requeue, idle-session migration via detach/restore,
honest in-flight failure), /healthz degradation, and greedy parity —
multi-replica output token-identical to one replica and to
models/generate.py."""

import threading
import time

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.obs import MetricsRegistry, parse_exposition
from lstm_tensorspark_tpu.serve import (
    QueueFullError,
    Request,
    ServeEngine,
    ServeServer,
)

_CFG = LMConfig(vocab_size=29, hidden_size=16, num_layers=1)


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(3), _CFG)


def _server(params, n, registry=None, **kw):
    engines = [
        ServeEngine(params, _CFG, num_slots=4, prefill_buckets=(4, 8),
                    batch_buckets=(1, 2), rng_seed=i,
                    **({"registry": registry} if registry is not None else {}))
        for i in range(n)
    ]
    kw.setdefault("max_active", 2)
    kw.setdefault("queue_size", 8)
    return ServeServer(engines if n > 1 else engines[0], **kw)


def _kill_replica(server, idx):
    """Crash one replica's scheduler thread: its next iteration raises,
    run() propagates, the thread exits — the death the router must detect
    on its next sweep."""
    boom = RuntimeError("injected scheduler crash")
    server.replicas[idx].batcher.step = (  # type: ignore[method-assign]
        lambda: (_ for _ in ()).throw(boom))
    t = server.replicas[idx].thread
    t.join(timeout=10.0)
    assert not t.is_alive()


# ---- routing ----------------------------------------------------------


def test_fresh_requests_spread_round_robin(params):
    """Sequential fresh requests on an idle 2-replica server alternate
    targets (least-loaded with a round-robin tie-break), so an idle fleet
    shares a burst instead of piling onto replica 0."""
    server = _server(params, 2)
    with server:
        seen = [server.generate([1, 2, 3], max_new_tokens=2).replica
                for _ in range(4)]
    assert set(seen) == {0, 1}, seen
    st = server.router.stats()
    assert st["routed"]["0"] == 2 and st["routed"]["1"] == 2


def _conversation(server):
    """A kept session advanced over 5 requests, with fresh traffic
    interleaved so pure least-loaded routing would prefer the OTHER
    replica. Returns (all session tokens, replica per session request)."""
    r = server.generate([1, 2, 3], max_new_tokens=2, keep_session=True)
    toks, homes, sid = list(r.tokens), [r.replica], r.session_id
    for _ in range(4):
        server.generate([2, 4], max_new_tokens=1)
        r = server.generate([toks[-1]], max_new_tokens=2, session_id=sid,
                            keep_session=True)
        toks.extend(r.tokens)
        homes.append(r.replica)
    return toks, homes


def test_session_affinity_sticks_across_windows(params):
    """Every continuation of a kept session lands on the replica holding
    its recurrent state, no matter how load shifts — the state cache IS
    the affinity table — and the conversation decodes token-identically
    to an uninterrupted single-replica run."""
    server = _server(params, 2)
    with server:
        toks, homes = _conversation(server)
    assert len(set(homes)) == 1, homes
    single = _server(params, 1)
    with single:
        ref, _ = _conversation(single)
    assert toks == ref


def test_global_queue_bound_429(params):
    """The router enforces ONE bound over the sum of replica queues —
    an unstarted server accepts exactly queue_size submissions, then
    429s, regardless of how routing spread them."""
    server = _server(params, 2, queue_size=3)
    reqs = [Request([1, 2], 2) for _ in range(3)]
    for r in reqs:
        server.router.submit(r)
    with pytest.raises(QueueFullError):
        server.router.submit(Request([1, 2], 2))
    assert server.router.stats()["rejected"] == 1
    # the accepted ones were spread over both replicas' queues
    routed = server.router.stats()["routed"]
    assert routed["0"] + routed["1"] == 3


def test_expired_session_fails_loudly_on_any_replica(params):
    """A continuation for a session NO replica holds routes by load and
    fails honestly — never silently decodes from zero state."""
    server = _server(params, 2)
    with server:
        with pytest.raises(RuntimeError, match="unknown session"):
            server.generate([5], max_new_tokens=2, session_id="nope")


def test_wedged_replica_excluded_from_fresh_routing(params):
    """A heartbeat-stale (wedged, thread-alive) replica stops receiving
    fresh sessions — they would hang to client timeout — but is never
    force-retired (its thread may wake and touch its structures)."""
    server = _server(params, 2, health_stale_after=0.2)

    def wedged_run(stop_event, idle_wait=0.05):
        server.replicas[1].batcher.last_heartbeat = time.monotonic()
        stop_event.wait()  # stuck "inside a device call"

    server.replicas[1].batcher.run = wedged_run  # type: ignore
    with server:
        time.sleep(0.5)  # let the heartbeat go stale
        assert server.health()["status"] == "degraded"
        for _ in range(4):
            assert server.generate([1, 2], max_new_tokens=2).replica == 0
        # wedged ≠ dead: never retired, nothing requeued/failed
        st = server.router.stats()
        assert st["retired"] == [] and st["failed_on_death"] == 0


# ---- parity -----------------------------------------------------------


def test_greedy_parity_multi_vs_single_vs_generate(params):
    """Greedy decode through 2 replicas is token-identical to 1 replica
    AND to models/generate.py — routing must not change a single token."""
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, _CFG.vocab_size, size=t).astype(np.int32)
               for t in (3, 5, 4, 2)]
    n_new = 6
    outs = {}
    for n in (1, 2):
        server = _server(params, n, max_active=4, queue_size=16)
        with server:
            got = [None] * len(prompts)

            def run_one(i, srv=server, out=got):
                out[i] = list(srv.generate(
                    prompts[i], max_new_tokens=n_new).tokens)

            threads = [threading.Thread(target=run_one, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        outs[n] = got
    assert outs[1] == outs[2]
    gen = make_generate_fn(_CFG, max_new_tokens=n_new, greedy=True)
    for prompt, got in zip(prompts, outs[2]):
        ref = np.asarray(gen(params, prompt[None, :],
                             jax.random.PRNGKey(3)))[0, prompt.size:]
        assert got == ref.tolist()


# ---- replica death ----------------------------------------------------


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_replica_death_degrades_healthz_and_survivors_serve(params):
    server = _server(params, 2)
    with server:
        server.generate([1, 2, 3], max_new_tokens=2)
        _kill_replica(server, 1)
        h = server.health()
        assert h["status"] == "degraded" and h["ok"] is False
        assert h["replicas_healthy"] == 1 and h["replicas_total"] == 2
        assert h["replicas"][1]["alive"] is False
        assert h["replicas"][1]["retired"] is True
        # the survivor keeps serving, and ALL new traffic routes to it
        for _ in range(3):
            req = server.generate([4, 5], max_new_tokens=2)
            assert req.replica == 0
        assert server.router.stats()["live"] == 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_replica_death_requeues_queued_requests(params):
    """Requests still waiting in a dead replica's queue are requeued onto
    a live replica by the next sweep and complete normally."""
    server = _server(params, 2)
    with server:
        _kill_replica(server, 1)
        # queue directly on the dead (not yet retired) replica's batcher —
        # the race a router submit that just picked it would lose
        req = Request(np.array([1, 2, 3], np.int32), 3)
        server.replicas[1].batcher.submit(req)
        server.health()  # probe triggers the sweep → retire → requeue
        assert req.done.wait(30.0)
        assert req.error is None and len(req.tokens) == 3
        assert req.replica == 0
        st = server.router.stats()
        assert st["requeued"] == 1 and st["retired"] == [1]


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_replica_death_migrates_idle_sessions_exactly(params):
    """An idle kept session on a dead replica migrates (detach/restore)
    to a survivor; its continuation decodes token-identically to an
    uninterrupted single-replica conversation."""
    # reference: uninterrupted conversation on one replica
    single = _server(params, 1)
    with single:
        r = single.generate([1, 2, 3], max_new_tokens=3, keep_session=True)
        ref = list(r.tokens)
        r2 = single.generate([ref[-1]], max_new_tokens=3,
                             session_id=r.session_id)
        ref += list(r2.tokens)

    server = _server(params, 2)
    with server:
        # occupy one replica first so the kept session lands on the other
        # (rr tie-break); the test adapts to whichever it actually used
        server.generate([9, 9], max_new_tokens=1, keep_session=True)
        kept = server.generate([1, 2, 3], max_new_tokens=3,
                               keep_session=True)
        victim = kept.replica
        assert kept.session_id in server.replicas[victim].engine.cache
        _kill_replica(server, victim)
        server.health()  # sweep: migrate the idle kept session
        st = server.router.stats()
        assert st["migrated_sessions"] >= 1
        survivor = 1 - victim
        assert kept.session_id in server.replicas[survivor].engine.cache
        cont = server.generate([kept.tokens[-1]], max_new_tokens=3,
                               session_id=kept.session_id)
        assert cont.replica == survivor
        assert list(kept.tokens) + list(cont.tokens) == ref


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_replica_death_fails_inflight_honestly(params):
    """A request actively decoding when its scheduler dies fails with an
    honest 'state lost' error instead of hanging until client timeout
    (its decode position is indeterminate under dispatch-ahead windows)."""
    server = _server(params, 1)
    with server:
        batcher = server.batcher
        real_step = batcher.step
        calls = [0]

        def dying_step():
            calls[0] += 1
            if calls[0] > 3:  # admit + decode a little first
                raise RuntimeError("injected scheduler crash")
            return real_step()

        batcher.step = dying_step  # type: ignore[method-assign]
        errs = []

        def client():
            try:
                server.generate([1, 2, 3], max_new_tokens=500, timeout=60.0)
            except RuntimeError as e:
                errs.append(str(e))

        t = threading.Thread(target=client)
        t.start()
        server.replicas[0].thread.join(timeout=10.0)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if server.health()["status"] == "down" and errs:
                break
            time.sleep(0.05)
        t.join(timeout=10.0)
        assert not t.is_alive()
        assert errs and "state lost" in errs[0], errs
        assert server.router.stats()["failed_on_death"] == 1
        # the failed session's slot was released — nothing leaks
        assert server.engine.cache.stats()["pinned"] == 0


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_restart_after_replica_death_revives_routing(params):
    """stop()/start() after a death clears retirement: the fresh
    scheduler threads serve again and the router routes to every
    replica (a still-set retired flag would 500 all traffic on a
    single-replica server while health smiled)."""
    server = _server(params, 2)
    with server:
        _kill_replica(server, 1)
        server.health()
        assert server.router.stats()["live"] == 1
    del server.replicas[1].batcher.step  # un-poison: restore class method
    server.start()
    try:
        assert server.health()["status"] == "ok"
        assert server.router.stats()["live"] == 2
        seen = {server.generate([1, 2], max_new_tokens=2).replica
                for _ in range(4)}
        assert seen == {0, 1}
    finally:
        server.stop()


# ---- replicated telemetry & stats ------------------------------------


def test_replica_labelled_metrics_and_aggregates(params):
    reg = MetricsRegistry()
    server = _server(params, 2, registry=reg, max_active=4, queue_size=16)
    with server:
        for _ in range(4):
            server.generate([1, 2, 3], max_new_tokens=2)
        fams = parse_exposition(server.metrics_text())
        for fam in ("serve_queue_depth", "serve_requests_total"):
            seen = {labels.get("replica")
                    for _, labels, _ in fams[fam]["samples"]}
            assert {"0", "1"} <= seen, (fam, seen)
        assert "serve_router_routed_total" in fams
        # summaries: per-child entries plus the bare-name aggregate
        ms = server.metrics_summary()
        agg = ms["serve_ttft_seconds"]
        assert agg["count"] == 4
        per = [v for k, v in ms.items()
               if k.startswith("serve_ttft_seconds{")]
        assert sum(p["count"] for p in per) == 4 and len(per) == 2
        st = server.stats()
        assert st["batcher"]["completed"] == 4
        assert sum(st["router"]["routed"].values()) == 4
        assert [r["replica"] for r in st["replicas"]] == [0, 1]
