"""graftlint (tools/lint): fixture-driven rule tests + gate contract.

Every rule has at least one true-positive fixture and one clean twin in
tests/lint_fixtures/ (the lock-order rule has three: the 2-lock ABBA,
the 3-lock cycle routed through a listener callback, and the shared-
RLock pattern that must NOT fire). The CLI contract under test is the
one tools/verify.sh gates on: exit 0 when clean / all findings
baselined, exit REGRESSION_RC (3 — imported from the one exit-code
table) on new findings, a ``GRAFTLINT new=N baseline=M`` summary line,
and ``--update-baseline`` / ``--json`` round-trips.

Pure-AST: no jax, no device, sub-second — safe in tier-1 ahead of the
timed suite's budget.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from lstm_tensorspark_tpu.resilience.exit_codes import (  # noqa: E402
    REGRESSION_RC,
    USAGE_RC,
)
from tools.lint import RULES, core, model  # noqa: E402
from tools.lint.__main__ import main as lint_main  # noqa: E402

FIXTURES = os.path.join(_REPO, "tests", "lint_fixtures")

#: fixture stem -> rule id it must (and must only) trigger
VIOLATIONS = {
    "viol_host_sync": "host-sync",
    "viol_tier_sync": "host-sync",
    "viol_decode_sync": "host-sync",
    "viol_warmup_pallas": "warmup-coverage",
    "viol_warmup_mesh": "warmup-coverage",
    "viol_warmup_train": "warmup-coverage",
    "viol_spec_warmup": "warmup-coverage",
    "viol_lock_abba": "lock-order",
    "viol_lock_listener": "lock-order",
    "viol_trie_lock": "lock-order",
    "viol_warmup": "warmup-coverage",
    "viol_exit_code": "exit-code-literal",
    "viol_metrics": "metrics-consistency",
    "viol_cross_thread": "cross-thread-state",
    "viol_wallclock": "wallclock-timing",
    "viol_midfile_import": "mid-file-import",
    "viol_resource_pair": "resource-pairing",
    "viol_thread_lifecycle": "thread-lifecycle",
    "viol_autotune": "thread-lifecycle",
    "viol_autotune_warmup": "warmup-coverage",
    "viol_rollout": "thread-lifecycle",
    "viol_rollout_warmup": "warmup-coverage",
    "viol_io_lock": "io-under-lock",
    "viol_remote_sync": "io-under-lock",
    "viol_toctou": "toctou-fs",
    "viol_swallowed": "swallowed-exception",
}

#: clean-twin stem -> the rule id it proves silent (the meta-test below
#: requires every registered rule to appear in BOTH tables)
CLEAN_TWINS = {
    "clean_host_sync": "host-sync",
    "clean_tier_sync": "host-sync",
    "clean_decode_sync": "host-sync",
    "clean_warmup_pallas": "warmup-coverage",
    "clean_warmup_mesh": "warmup-coverage",
    "clean_warmup_train": "warmup-coverage",
    "clean_spec_warmup": "warmup-coverage",
    "clean_lock_order": "lock-order",
    "clean_lock_shared_rlock": "lock-order",
    "clean_trie_lock": "lock-order",
    "clean_warmup": "warmup-coverage",
    "clean_exit_code": "exit-code-literal",
    "clean_metrics": "metrics-consistency",
    "clean_cross_thread": "cross-thread-state",
    "clean_wallclock": "wallclock-timing",
    "clean_midfile_import": "mid-file-import",
    "clean_resource_pair": "resource-pairing",
    "clean_thread_lifecycle": "thread-lifecycle",
    "clean_autotune": "thread-lifecycle",
    "clean_autotune_warmup": "warmup-coverage",
    "clean_rollout": "thread-lifecycle",
    "clean_rollout_warmup": "warmup-coverage",
    "clean_io_lock": "io-under-lock",
    "clean_remote_sync": "io-under-lock",
    "clean_toctou": "toctou-fs",
    "clean_swallowed": "swallowed-exception",
}


def _lint(*argv) -> int:
    return lint_main(list(argv))


def _findings_for(path: str):
    project = model.load_project([path], FIXTURES)
    return core.run_rules(project)


# ---- rule catalogue ----------------------------------------------------

def test_at_least_thirteen_rules_registered():
    assert len(RULES) >= 13, sorted(RULES)
    for required in ("host-sync", "lock-order", "warmup-coverage",
                     "exit-code-literal", "metrics-consistency",
                     "cross-thread-state", "resource-pairing",
                     "thread-lifecycle", "io-under-lock", "toctou-fs",
                     "swallowed-exception"):
        assert required in RULES


def test_every_rule_has_fixture_pair_and_doc_row():
    """Meta-test: a rule can never land undocumented or untested. Every
    registered rule must have (a) a violation fixture wired into
    VIOLATIONS, (b) a clean twin wired into CLEAN_TWINS, (c) both
    fixture files on disk, and (d) a `rule-id` row in docs/LINT.md's
    catalogue table."""
    viol_rules = set(VIOLATIONS.values())
    clean_rules = set(CLEAN_TWINS.values())
    with open(os.path.join(_REPO, "docs", "LINT.md")) as f:
        lint_md = f.read()
    for rule_id in RULES:
        assert rule_id in viol_rules, (
            f"rule {rule_id!r} has no violation fixture in VIOLATIONS")
        assert rule_id in clean_rules, (
            f"rule {rule_id!r} has no clean twin in CLEAN_TWINS")
        assert f"| `{rule_id}` |" in lint_md, (
            f"rule {rule_id!r} has no docs/LINT.md catalogue row")
    for stem in [*VIOLATIONS, *CLEAN_TWINS]:
        assert os.path.exists(os.path.join(FIXTURES, stem + ".py")), (
            f"fixture file {stem}.py is missing")
    # and the tables only name registered rules (no orphaned coverage)
    for rule_id in viol_rules | clean_rules:
        assert rule_id in RULES, f"fixture table names unknown {rule_id!r}"


@pytest.mark.parametrize("stem,rule_id", sorted(VIOLATIONS.items()))
def test_violation_fixture_fires_exactly_its_rule(stem, rule_id):
    path = os.path.join(FIXTURES, stem + ".py")
    findings = _findings_for(path)
    assert findings, f"{stem} produced no findings"
    assert {f.rule for f in findings} == {rule_id}, findings


@pytest.mark.parametrize("stem,rule_id", sorted(VIOLATIONS.items()))
def test_violation_fixture_exits_regression_rc(stem, rule_id, capsys):
    rc = _lint(os.path.join(FIXTURES, stem + ".py"),
               "--no-baseline", "--root", FIXTURES)
    captured = capsys.readouterr().out
    assert rc == REGRESSION_RC
    assert rule_id in captured
    # the verify.sh summary line, with a real new count
    assert "GRAFTLINT new=" in captured
    assert "GRAFTLINT new=0" not in captured


@pytest.mark.parametrize("stem", CLEAN_TWINS)
def test_clean_twin_is_clean(stem, capsys):
    rc = _lint(os.path.join(FIXTURES, stem + ".py"),
               "--no-baseline", "--root", FIXTURES)
    assert rc == 0
    assert "GRAFTLINT new=0 baseline=0" in capsys.readouterr().out


# ---- specific rule semantics ------------------------------------------

def test_lock_order_abba_cycle_names_both_locks():
    findings = _findings_for(os.path.join(FIXTURES, "viol_lock_abba.py"))
    msg = " | ".join(f.message for f in findings)
    assert "Ledger._alock" in msg and "Ledger._block" in msg
    assert "cycle" in msg


def test_lock_order_listener_cycle_spans_three_locks():
    findings = _findings_for(
        os.path.join(FIXTURES, "viol_lock_listener.py"))
    msgs = [f.message for f in findings]
    # the 3-class cycle closed by the callback edge is reported
    assert any("Cache._lock" in m and "Index._lock" in m
               and "Store._lock" in m for m in msgs), msgs
    assert any("evict_listeners" in m for m in msgs), msgs


def test_shared_rlock_pattern_does_not_fire():
    findings = _findings_for(
        os.path.join(FIXTURES, "clean_lock_shared_rlock.py"))
    assert findings == []


def test_warmup_finding_names_the_missing_family():
    findings = _findings_for(os.path.join(FIXTURES, "viol_warmup.py"))
    assert len(findings) == 1
    assert "'decode_beam'" in findings[0].message


def test_suppression_pragma_silences_the_rule():
    # clean_wallclock contains a time.time() call carrying the pragma —
    # prove the call is there AND that it does not surface
    path = os.path.join(FIXTURES, "clean_wallclock.py")
    with open(path) as f:
        src = f.read()
    assert "time.time()" in src
    assert "graftlint: disable=wallclock-timing" in src
    assert _findings_for(path) == []


def test_resource_pairing_accepts_except_reraise_with_finally(tmp_path):
    """The canonical try/except-log-reraise/finally-release idiom must
    NOT fire: a handler's re-raise runs the finally (and its release)
    before leaving the function."""
    (tmp_path / "m.py").write_text(
        "class W:\n"
        "    def __init__(self, cache, disk):\n"
        "        self.cache = cache\n"
        "        self.disk = disk\n"
        "    def snap(self, sid):\n"
        "        self.cache.pin(sid)\n"
        "        try:\n"
        "            return self.disk.read(sid)\n"
        "        except Exception:\n"
        "            self.log(sid)\n"
        "            raise\n"
        "        finally:\n"
        "            self.cache.unpin(sid)\n"
        "    def log(self, sid):\n"
        "        print(sid)\n")
    project = model.load_project([str(tmp_path)], str(tmp_path))
    findings = [f for f in core.run_rules(project)
                if f.rule == "resource-pairing"]
    assert findings == [], findings


def test_thread_lifecycle_pairs_init_store_with_start_method(tmp_path):
    """Thread constructed in __init__, started from start(): the store
    and the start must pair ACROSS methods — this is the most common
    idiom of the leaked-poller class."""
    (tmp_path / "m.py").write_text(
        "import threading\n"
        "\n"
        "\n"
        "class Poller:\n"
        "    def __init__(self):\n"
        "        self._thread = threading.Thread(\n"
        "            target=self._loop, daemon=True)\n"
        "    def start(self):\n"
        "        self._thread.start()\n"
        "    def _loop(self):\n"
        "        while True:\n"
        "            pass\n")
    project = model.load_project([str(tmp_path)], str(tmp_path))
    findings = [f for f in core.run_rules(project)
                if f.rule == "thread-lifecycle"]
    assert len(findings) == 1, findings
    assert "Poller._thread" in findings[0].message


def test_resource_pairing_accepts_return_inside_try_finally(tmp_path):
    """`try: return work() finally: release` — the return runs the
    finally first; the CFG must route it through, not straight to
    EXIT (the value here deliberately does NOT mention the key, so
    escape analysis cannot be what silences it)."""
    (tmp_path / "m.py").write_text(
        "class W:\n"
        "    def __init__(self, cache, disk):\n"
        "        self.cache = cache\n"
        "        self.disk = disk\n"
        "    def snap(self, sid):\n"
        "        self.cache.pin(sid)\n"
        "        try:\n"
        "            return self.disk.read_all()\n"
        "        finally:\n"
        "            self.cache.unpin(sid)\n")
    project = model.load_project([str(tmp_path)], str(tmp_path))
    findings = [f for f in core.run_rules(project)
                if f.rule == "resource-pairing"]
    assert findings == [], findings


def test_resource_pairing_reports_exception_path():
    findings = _findings_for(
        os.path.join(FIXTURES, "viol_resource_pair.py"))
    msgs = [f.message for f in findings]
    assert any("pinned slot" in m and "exception path" in m
               for m in msgs), msgs
    assert any("counter" in m and "_in_flight" in m for m in msgs), msgs


def test_io_under_lock_names_the_callee_chain():
    findings = _findings_for(os.path.join(FIXTURES, "viol_io_lock.py"))
    msgs = [f.message for f in findings]
    # direct IO under the lock AND IO reached through a resolvable callee
    assert any("open()" in m and "StateCache._lock" in m
               for m in msgs), msgs
    assert any("Store.persist" in m and "os.replace()" in m
               for m in msgs), msgs


def test_thread_lifecycle_names_the_attr():
    findings = _findings_for(
        os.path.join(FIXTURES, "viol_thread_lifecycle.py"))
    assert len(findings) == 1
    assert "Poller._thread" in findings[0].message


def test_toctou_names_the_path_expression():
    findings = _findings_for(os.path.join(FIXTURES, "viol_toctou.py"))
    msgs = [f.message for f in findings]
    assert any("remove()" in m and "(side)" in m for m in msgs), msgs
    assert any("open()" in m and "(path)" in m for m in msgs), msgs


def test_swallowed_exception_scoped_to_scheduler_closure():
    findings = _findings_for(os.path.join(FIXTURES, "viol_swallowed.py"))
    assert len(findings) == 1
    assert "Batcher.step" in findings[0].message
    # the clean twin keeps a catch-all-pass OUTSIDE the closure (stats)
    # plus a narrow except inside it — both must stay silent
    assert _findings_for(
        os.path.join(FIXTURES, "clean_swallowed.py")) == []


def test_wallclock_catches_alias_and_datetime_duration():
    findings = _findings_for(os.path.join(FIXTURES, "viol_wallclock.py"))
    msgs = [f.message for f in findings]
    assert any("from time import time" in m for m in msgs), msgs
    assert any("datetime.now()" in m for m in msgs), msgs
    assert any(m.startswith("time.time()") for m in msgs), msgs


# ---- suppression span robustness (decorators / multi-line with) --------

def test_suppression_above_decorated_def(tmp_path):
    (tmp_path / "m.py").write_text(
        "import time\n"
        "\n"
        "\n"
        "def deco(f):\n"
        "    return f\n"
        "\n"
        "\n"
        "# wall-clock default is deliberate here\n"
        "# graftlint: disable=wallclock-timing\n"
        "@deco\n"
        "@deco\n"
        "def stamp(t0=time.time()):\n"
        "    return t0\n")
    project = model.load_project([str(tmp_path)], str(tmp_path))
    assert core.run_rules(project) == []
    # and WITHOUT the pragma the same shape fires (the test is honest)
    (tmp_path / "m.py").write_text(
        (tmp_path / "m.py").read_text().replace(
            "# graftlint: disable=wallclock-timing\n", ""))
    project = model.load_project([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in core.run_rules(project)] == [
        "wallclock-timing"]


def test_suppression_inside_multiline_with_header(tmp_path):
    (tmp_path / "m.py").write_text(
        "import time\n"
        "\n"
        "\n"
        "def hold(res):\n"
        "    with res(  # graftlint: disable=wallclock-timing\n"
        "        time.time()\n"
        "    ) as f:\n"
        "        return f\n")
    project = model.load_project([str(tmp_path)], str(tmp_path))
    assert core.run_rules(project) == []
    (tmp_path / "m.py").write_text(
        (tmp_path / "m.py").read_text().replace(
            "  # graftlint: disable=wallclock-timing", ""))
    project = model.load_project([str(tmp_path)], str(tmp_path))
    assert [f.rule for f in core.run_rules(project)] == [
        "wallclock-timing"]


# ---- CLI / gate contract ----------------------------------------------

def test_usage_rc_on_bad_path():
    assert _lint("/nonexistent/graftlint/path") == USAGE_RC


def test_usage_rc_on_unknown_rule():
    assert _lint("--rules", "no-such-rule",
                 os.path.join(FIXTURES, "clean_exit_code.py")) == USAGE_RC


def test_update_baseline_then_clean(tmp_path, capsys):
    baseline = str(tmp_path / "baseline.txt")
    viol = os.path.join(FIXTURES, "viol_exit_code.py")
    # gate fires with an empty baseline
    assert _lint(viol, "--baseline", baseline,
                 "--root", FIXTURES) == REGRESSION_RC
    # record, with justification placeholders
    assert _lint(viol, "--baseline", baseline, "--update-baseline",
                 "--root", FIXTURES) == 0
    text = open(baseline).read()
    assert "viol_exit_code.py:exit-code-literal:" in text
    assert "#" in text  # justification column exists
    # baselined findings no longer gate...
    capsys.readouterr()
    assert _lint(viol, "--baseline", baseline, "--root", FIXTURES) == 0
    out = capsys.readouterr().out
    assert "GRAFTLINT new=0 baseline=3" in out
    # ...but are still printed (without the NEW tag)
    assert "exit-code-literal" in out and "[NEW]" not in out


def test_baseline_justifications_survive_update(tmp_path):
    baseline = str(tmp_path / "baseline.txt")
    viol = os.path.join(FIXTURES, "viol_wallclock.py")
    _lint(viol, "--baseline", baseline, "--update-baseline",
          "--root", FIXTURES)
    # a human fills in the justification
    text = open(baseline).read().replace("TODO: justify or fix",
                                         "measured against an epoch file")
    with open(baseline, "w") as f:
        f.write(text)
    _lint(viol, "--baseline", baseline, "--update-baseline",
          "--root", FIXTURES)
    assert "measured against an epoch file" in open(baseline).read()


def test_json_report(tmp_path, capsys):
    out_json = str(tmp_path / "lint.json")
    viol = os.path.join(FIXTURES, "viol_metrics.py")
    rc = _lint(viol, "--no-baseline", "--root", FIXTURES,
               "--json", out_json)
    assert rc == REGRESSION_RC
    payload = json.load(open(out_json))
    assert payload["new"] == len(payload["findings"]) > 0
    assert payload["by_rule"] == {
        "metrics-consistency": len(payload["findings"])}
    for f in payload["findings"]:
        assert f["new"] is True
        assert f["rule"] == "metrics-consistency"
        assert f["rel"] and f["line"] >= 1 and f["key"]


def test_rules_filter_runs_only_selected(capsys):
    viol = os.path.join(FIXTURES, "viol_exit_code.py")
    rc = _lint(viol, "--no-baseline", "--root", FIXTURES,
               "--rules", "wallclock-timing")
    assert rc == 0  # the exit-code findings exist but that rule is off
    assert "GRAFTLINT new=0" in capsys.readouterr().out


def test_finding_key_is_line_number_free():
    findings = _findings_for(os.path.join(FIXTURES, "viol_warmup.py"))
    key = findings[0].key()
    assert str(findings[0].line) + ":" not in key
    assert key.startswith("viol_warmup.py:warmup-coverage:")


# ---- --changed scoped mode ---------------------------------------------

def _git(repo, *args):
    import subprocess
    return subprocess.run(
        ["git", "-C", str(repo), *args], capture_output=True, text=True,
        check=True,
        env={**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL":
             "t@t", "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL":
             "t@t", "HOME": str(repo)})


def test_changed_mode_lints_changed_files_and_importers(tmp_path, capsys):
    _git(tmp_path, "init", "-q")
    (tmp_path / "a.py").write_text("VALUE = 1\n")
    # b imports a and carries a violation that predates the change
    (tmp_path / "b.py").write_text(
        "import time\n"
        "import a\n"
        "\n"
        "\n"
        "def timed():\n"
        "    return time.time(), a.VALUE\n")
    # c is unrelated and ALSO carries a violation — scoped mode must
    # not report it
    (tmp_path / "c.py").write_text(
        "import time\n"
        "\n"
        "\n"
        "def other():\n"
        "    return time.time()\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    # change ONLY a.py in the working tree
    (tmp_path / "a.py").write_text("VALUE = 2\n")
    rc = _lint(str(tmp_path), "--changed", "HEAD", "--no-baseline",
               "--root", str(tmp_path))
    out = capsys.readouterr().out
    assert rc == REGRESSION_RC
    assert "b.py" in out          # importer of the changed module
    assert "c.py" not in out      # unrelated: out of scope
    # nothing changed -> clean run over an empty scope
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "update")
    assert _lint(str(tmp_path), "--changed", "HEAD", "--no-baseline",
                 "--root", str(tmp_path)) == 0


def test_changed_mode_includes_package_init_importer(tmp_path, capsys):
    """`from . import mod` inside pkg/__init__.py must resolve to
    pkg.mod, so changing pkg/mod.py pulls the __init__ into scope."""
    _git(tmp_path, "init", "-q")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(
        "import time\n"
        "from . import mod\n"
        "\n"
        "STARTED = time.time()  # the violation lives in the importer\n")
    (pkg / "mod.py").write_text("VALUE = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "mod.py").write_text("VALUE = 2\n")
    rc = _lint(str(tmp_path), "--changed", "HEAD", "--no-baseline",
               "--root", str(tmp_path))
    out = capsys.readouterr().out
    assert rc == REGRESSION_RC
    assert "pkg/__init__.py" in out


def test_changed_closure_includes_the_changed_files_imports(tmp_path):
    """The changed file's OWN imports join the scope (one hop): without
    them cross-module resolution degrades and a scoped run could
    over-report — the one thing it must never do."""
    (tmp_path / "helper.py").write_text("class Helper:\n    pass\n")
    (tmp_path / "a.py").write_text("import helper\nH = helper.Helper\n")
    (tmp_path / "c.py").write_text("VALUE = 3\n")
    project = model.load_project([str(tmp_path)], str(tmp_path))
    scope = model.changed_closure(project, {"a.py"})
    assert "helper.py" in scope       # a.py's import
    assert "c.py" not in scope        # unrelated


def test_scoped_json_report_does_not_poison_the_trend(tmp_path, capsys):
    """A --changed run writes its report flagged scoped; neither it nor
    the next full run prints deltas against mismatched universes."""
    _git(tmp_path, "init", "-q")
    (tmp_path / "a.py").write_text("VALUE = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    out_json = str(tmp_path / "LINT_report.json")
    viol = os.path.join(FIXTURES, "viol_wallclock.py")
    # seed a FULL report with findings
    _lint(viol, "--no-baseline", "--root", FIXTURES, "--json", out_json)
    capsys.readouterr()
    # scoped run (empty scope): report flagged scoped, NO deltas printed
    rc = _lint(str(tmp_path), "--changed", "HEAD", "--no-baseline",
               "--root", str(tmp_path), "--json", out_json)
    assert rc == 0
    summary = [ln for ln in capsys.readouterr().out.splitlines()
               if ln.startswith("GRAFTLINT")][0]
    assert "d(" not in summary
    assert json.load(open(out_json))["scoped"] is True
    # next full run: previous report is scoped -> still no deltas
    _lint(viol, "--no-baseline", "--root", FIXTURES, "--json", out_json)
    summary = [ln for ln in capsys.readouterr().out.splitlines()
               if ln.startswith("GRAFTLINT")][0]
    assert "d(" not in summary
    assert json.load(open(out_json))["scoped"] is False


def test_changed_mode_covers_untracked_files(tmp_path, capsys):
    """A brand-new not-yet-added module is the likeliest carrier of
    fresh violations — pre-commit mode must see it."""
    _git(tmp_path, "init", "-q")
    (tmp_path / "a.py").write_text("VALUE = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (tmp_path / "new.py").write_text(
        "import time\n"
        "\n"
        "\n"
        "def timed():\n"
        "    return time.time()\n")
    rc = _lint(str(tmp_path), "--changed", "HEAD", "--no-baseline",
               "--root", str(tmp_path))
    out = capsys.readouterr().out
    assert rc == REGRESSION_RC
    assert "new.py" in out


def test_changed_mode_rejects_update_baseline(tmp_path, capsys):
    """--changed + --update-baseline would rewrite the baseline from
    the SCOPED finding set, silently deleting every out-of-scope entry
    and its justification — refused as a usage error."""
    _git(tmp_path, "init", "-q")
    (tmp_path / "a.py").write_text("VALUE = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    assert _lint(str(tmp_path), "--changed", "HEAD", "--update-baseline",
                 "--root", str(tmp_path)) == USAGE_RC


def test_changed_mode_bad_ref_is_usage_error(tmp_path):
    _git(tmp_path, "init", "-q")
    (tmp_path / "a.py").write_text("VALUE = 1\n")
    assert _lint(str(tmp_path), "--changed", "no-such-ref",
                 "--root", str(tmp_path)) == USAGE_RC


# ---- per-rule deltas vs the previous --json report ---------------------

def test_json_report_grows_per_rule_deltas(tmp_path, capsys):
    out_json = str(tmp_path / "LINT_report.json")
    viol = os.path.join(FIXTURES, "viol_wallclock.py")
    clean = os.path.join(FIXTURES, "clean_wallclock.py")
    # first run: no previous report -> no delta suffix
    _lint(viol, "--no-baseline", "--root", FIXTURES, "--json", out_json)
    first = capsys.readouterr().out
    summary = [ln for ln in first.splitlines()
               if ln.startswith("GRAFTLINT")][0]
    assert "d(" not in summary
    n_viol = json.load(open(out_json))["by_rule"]["wallclock-timing"]
    # second run against the clean twin: the summary line carries the
    # per-rule delta vs the previous report at the same path
    _lint(clean, "--no-baseline", "--root", FIXTURES, "--json", out_json)
    second = capsys.readouterr().out
    summary = [ln for ln in second.splitlines()
               if ln.startswith("GRAFTLINT")][0]
    assert f"d(wallclock-timing)={-n_viol:+d}" in summary
    # unchanged re-run: zero deltas are not printed
    _lint(clean, "--no-baseline", "--root", FIXTURES, "--json", out_json)
    summary = [ln for ln in capsys.readouterr().out.splitlines()
               if ln.startswith("GRAFTLINT")][0]
    assert "d(" not in summary


# ---- review-hardening regressions -------------------------------------

def test_same_named_classes_in_two_modules_do_not_alias(tmp_path):
    """Lock identities and method facts are module-qualified: class
    `Worker` in a.py (guarded attr + clean locking) must not inherit
    findings from an unrelated `Worker` in b.py."""
    (tmp_path / "a.py").write_text(
        "import threading\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.jobs = []\n"
        "    def put(self, j):\n"
        "        with self._lock:\n"
        "            self.jobs.append(j)\n"
        "    def stats(self):\n"
        "        with self._lock:\n"
        "            return len(self.jobs)\n")
    (tmp_path / "b.py").write_text(
        "import threading\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.jobs = []\n"
        "    def put(self, j):\n"
        "        with self._lock:\n"
        "            self.jobs.append(j)\n"
        "    def stats(self):\n"
        "        return len(self.jobs)\n")  # unguarded: b.py's bug only
    project = model.load_project([str(tmp_path)], str(tmp_path))
    findings = core.run_rules(project)
    assert [f.rel for f in findings] == ["b.py"], findings
    assert findings[0].rule == "cross-thread-state"


def test_module_level_metric_registration_is_visible(tmp_path):
    """A module-scope registration (`M = reg.counter(...)` at import
    time) must satisfy consistency checks and labels() resolution."""
    (tmp_path / "m.py").write_text(
        "import registry as reg\n"
        "REQS = reg.counter('probe_total', 'requests',\n"
        "                   labelnames=('outcome',))\n"
        "OK = REQS.labels(outcome='ok')\n"
        "def record():\n"
        "    REQS.labels(status='bad')\n")  # wrong key: must be caught
    project = model.load_project([str(tmp_path)], str(tmp_path))
    findings = [f for f in core.run_rules(project)
                if f.rule == "metrics-consistency"]
    # the module-level labels(outcome=) call is clean; only the
    # function's labels(status=) mismatches — and registration itself
    # is visible (no 'not registered' style noise)
    assert len(findings) == 1, findings
    assert "status" in findings[0].message


def test_update_baseline_with_no_baseline_keeps_justifications(tmp_path):
    baseline = str(tmp_path / "baseline.txt")
    viol = os.path.join(FIXTURES, "viol_midfile_import.py")
    _lint(viol, "--baseline", baseline, "--update-baseline",
          "--root", FIXTURES)
    text = open(baseline).read().replace("TODO: justify or fix",
                                         "kept on purpose")
    with open(baseline, "w") as f:
        f.write(text)
    # --no-baseline only affects GATING; the rewrite must still merge
    # the existing file's hand-written justifications
    _lint(viol, "--baseline", baseline, "--no-baseline",
          "--update-baseline", "--root", FIXTURES)
    assert "kept on purpose" in open(baseline).read()


# ---- the tree itself ---------------------------------------------------

def test_full_tree_run_fits_phase0_budget():
    """verify.sh phase 0's whole value is failing in seconds, before
    the ~15-min timed suite — the full-tree all-rules run must stay
    under the documented 10 s budget (docs/OPERATIONS.md). Measured
    ~2–3 s today; a rule that re-walks the tree per finding instead of
    memoizing in the shared model fails here loudly."""
    import time
    t0 = time.monotonic()
    project = model.load_project(
        [os.path.join(_REPO, "lstm_tensorspark_tpu"),
         os.path.join(_REPO, "tools")], _REPO)
    core.run_rules(project)
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"full-tree lint took {elapsed:.1f}s (>10s)"


def test_repo_tree_is_lint_clean():
    """The acceptance invariant verify.sh gates on, asserted in tier-1
    too: the production tree (lstm_tensorspark_tpu/ + tools/) has zero
    findings outside tools/lint_baseline.txt, and every baseline entry
    carries a real justification."""
    project = model.load_project(
        [os.path.join(_REPO, "lstm_tensorspark_tpu"),
         os.path.join(_REPO, "tools")], _REPO)
    findings = core.run_rules(project)
    baseline = core.load_baseline(
        os.path.join(_REPO, "tools", "lint_baseline.txt"))
    new = [f for f in findings if f.key() not in baseline]
    assert new == [], "\n".join(f.render() for f in new)
    for key, justification in baseline.items():
        assert justification and "TODO" not in justification, (
            f"baseline entry {key} lacks a real justification")
