"""graftlint (tools/lint): fixture-driven rule tests + gate contract.

Every rule has at least one true-positive fixture and one clean twin in
tests/lint_fixtures/ (the lock-order rule has three: the 2-lock ABBA,
the 3-lock cycle routed through a listener callback, and the shared-
RLock pattern that must NOT fire). The CLI contract under test is the
one tools/verify.sh gates on: exit 0 when clean / all findings
baselined, exit REGRESSION_RC (3 — imported from the one exit-code
table) on new findings, a ``GRAFTLINT new=N baseline=M`` summary line,
and ``--update-baseline`` / ``--json`` round-trips.

Pure-AST: no jax, no device, sub-second — safe in tier-1 ahead of the
timed suite's budget.
"""

from __future__ import annotations

import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from lstm_tensorspark_tpu.resilience.exit_codes import (  # noqa: E402
    REGRESSION_RC,
    USAGE_RC,
)
from tools.lint import RULES, core, model  # noqa: E402
from tools.lint.__main__ import main as lint_main  # noqa: E402

FIXTURES = os.path.join(_REPO, "tests", "lint_fixtures")

#: fixture stem -> rule id it must (and must only) trigger
VIOLATIONS = {
    "viol_host_sync": "host-sync",
    "viol_tier_sync": "host-sync",
    "viol_lock_abba": "lock-order",
    "viol_lock_listener": "lock-order",
    "viol_warmup": "warmup-coverage",
    "viol_exit_code": "exit-code-literal",
    "viol_metrics": "metrics-consistency",
    "viol_cross_thread": "cross-thread-state",
    "viol_wallclock": "wallclock-timing",
    "viol_midfile_import": "mid-file-import",
}

CLEAN_TWINS = [
    "clean_host_sync",
    "clean_tier_sync",
    "clean_lock_order",
    "clean_lock_shared_rlock",
    "clean_warmup",
    "clean_exit_code",
    "clean_metrics",
    "clean_cross_thread",
    "clean_wallclock",
    "clean_midfile_import",
]


def _lint(*argv) -> int:
    return lint_main(list(argv))


def _findings_for(path: str):
    project = model.load_project([path], FIXTURES)
    return core.run_rules(project)


# ---- rule catalogue ----------------------------------------------------

def test_at_least_six_rules_registered():
    assert len(RULES) >= 6, sorted(RULES)
    for required in ("host-sync", "lock-order", "warmup-coverage",
                     "exit-code-literal", "metrics-consistency",
                     "cross-thread-state"):
        assert required in RULES


@pytest.mark.parametrize("stem,rule_id", sorted(VIOLATIONS.items()))
def test_violation_fixture_fires_exactly_its_rule(stem, rule_id):
    path = os.path.join(FIXTURES, stem + ".py")
    findings = _findings_for(path)
    assert findings, f"{stem} produced no findings"
    assert {f.rule for f in findings} == {rule_id}, findings


@pytest.mark.parametrize("stem,rule_id", sorted(VIOLATIONS.items()))
def test_violation_fixture_exits_regression_rc(stem, rule_id, capsys):
    rc = _lint(os.path.join(FIXTURES, stem + ".py"),
               "--no-baseline", "--root", FIXTURES)
    captured = capsys.readouterr().out
    assert rc == REGRESSION_RC
    assert rule_id in captured
    # the verify.sh summary line, with a real new count
    assert "GRAFTLINT new=" in captured
    assert "GRAFTLINT new=0" not in captured


@pytest.mark.parametrize("stem", CLEAN_TWINS)
def test_clean_twin_is_clean(stem, capsys):
    rc = _lint(os.path.join(FIXTURES, stem + ".py"),
               "--no-baseline", "--root", FIXTURES)
    assert rc == 0
    assert "GRAFTLINT new=0 baseline=0" in capsys.readouterr().out


# ---- specific rule semantics ------------------------------------------

def test_lock_order_abba_cycle_names_both_locks():
    findings = _findings_for(os.path.join(FIXTURES, "viol_lock_abba.py"))
    msg = " | ".join(f.message for f in findings)
    assert "Ledger._alock" in msg and "Ledger._block" in msg
    assert "cycle" in msg


def test_lock_order_listener_cycle_spans_three_locks():
    findings = _findings_for(
        os.path.join(FIXTURES, "viol_lock_listener.py"))
    msgs = [f.message for f in findings]
    # the 3-class cycle closed by the callback edge is reported
    assert any("Cache._lock" in m and "Index._lock" in m
               and "Store._lock" in m for m in msgs), msgs
    assert any("evict_listeners" in m for m in msgs), msgs


def test_shared_rlock_pattern_does_not_fire():
    findings = _findings_for(
        os.path.join(FIXTURES, "clean_lock_shared_rlock.py"))
    assert findings == []


def test_warmup_finding_names_the_missing_family():
    findings = _findings_for(os.path.join(FIXTURES, "viol_warmup.py"))
    assert len(findings) == 1
    assert "'decode_beam'" in findings[0].message


def test_suppression_pragma_silences_the_rule():
    # clean_wallclock contains a time.time() call carrying the pragma —
    # prove the call is there AND that it does not surface
    path = os.path.join(FIXTURES, "clean_wallclock.py")
    with open(path) as f:
        src = f.read()
    assert "time.time()" in src
    assert "graftlint: disable=wallclock-timing" in src
    assert _findings_for(path) == []


# ---- CLI / gate contract ----------------------------------------------

def test_usage_rc_on_bad_path():
    assert _lint("/nonexistent/graftlint/path") == USAGE_RC


def test_usage_rc_on_unknown_rule():
    assert _lint("--rules", "no-such-rule",
                 os.path.join(FIXTURES, "clean_exit_code.py")) == USAGE_RC


def test_update_baseline_then_clean(tmp_path, capsys):
    baseline = str(tmp_path / "baseline.txt")
    viol = os.path.join(FIXTURES, "viol_exit_code.py")
    # gate fires with an empty baseline
    assert _lint(viol, "--baseline", baseline,
                 "--root", FIXTURES) == REGRESSION_RC
    # record, with justification placeholders
    assert _lint(viol, "--baseline", baseline, "--update-baseline",
                 "--root", FIXTURES) == 0
    text = open(baseline).read()
    assert "viol_exit_code.py:exit-code-literal:" in text
    assert "#" in text  # justification column exists
    # baselined findings no longer gate...
    capsys.readouterr()
    assert _lint(viol, "--baseline", baseline, "--root", FIXTURES) == 0
    out = capsys.readouterr().out
    assert "GRAFTLINT new=0 baseline=3" in out
    # ...but are still printed (without the NEW tag)
    assert "exit-code-literal" in out and "[NEW]" not in out


def test_baseline_justifications_survive_update(tmp_path):
    baseline = str(tmp_path / "baseline.txt")
    viol = os.path.join(FIXTURES, "viol_wallclock.py")
    _lint(viol, "--baseline", baseline, "--update-baseline",
          "--root", FIXTURES)
    # a human fills in the justification
    text = open(baseline).read().replace("TODO: justify or fix",
                                         "measured against an epoch file")
    with open(baseline, "w") as f:
        f.write(text)
    _lint(viol, "--baseline", baseline, "--update-baseline",
          "--root", FIXTURES)
    assert "measured against an epoch file" in open(baseline).read()


def test_json_report(tmp_path, capsys):
    out_json = str(tmp_path / "lint.json")
    viol = os.path.join(FIXTURES, "viol_metrics.py")
    rc = _lint(viol, "--no-baseline", "--root", FIXTURES,
               "--json", out_json)
    assert rc == REGRESSION_RC
    payload = json.load(open(out_json))
    assert payload["new"] == len(payload["findings"]) > 0
    assert payload["by_rule"] == {
        "metrics-consistency": len(payload["findings"])}
    for f in payload["findings"]:
        assert f["new"] is True
        assert f["rule"] == "metrics-consistency"
        assert f["rel"] and f["line"] >= 1 and f["key"]


def test_rules_filter_runs_only_selected(capsys):
    viol = os.path.join(FIXTURES, "viol_exit_code.py")
    rc = _lint(viol, "--no-baseline", "--root", FIXTURES,
               "--rules", "wallclock-timing")
    assert rc == 0  # the exit-code findings exist but that rule is off
    assert "GRAFTLINT new=0" in capsys.readouterr().out


def test_finding_key_is_line_number_free():
    findings = _findings_for(os.path.join(FIXTURES, "viol_warmup.py"))
    key = findings[0].key()
    assert str(findings[0].line) + ":" not in key
    assert key.startswith("viol_warmup.py:warmup-coverage:")


# ---- review-hardening regressions -------------------------------------

def test_same_named_classes_in_two_modules_do_not_alias(tmp_path):
    """Lock identities and method facts are module-qualified: class
    `Worker` in a.py (guarded attr + clean locking) must not inherit
    findings from an unrelated `Worker` in b.py."""
    (tmp_path / "a.py").write_text(
        "import threading\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.jobs = []\n"
        "    def put(self, j):\n"
        "        with self._lock:\n"
        "            self.jobs.append(j)\n"
        "    def stats(self):\n"
        "        with self._lock:\n"
        "            return len(self.jobs)\n")
    (tmp_path / "b.py").write_text(
        "import threading\n"
        "class Worker:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.jobs = []\n"
        "    def put(self, j):\n"
        "        with self._lock:\n"
        "            self.jobs.append(j)\n"
        "    def stats(self):\n"
        "        return len(self.jobs)\n")  # unguarded: b.py's bug only
    project = model.load_project([str(tmp_path)], str(tmp_path))
    findings = core.run_rules(project)
    assert [f.rel for f in findings] == ["b.py"], findings
    assert findings[0].rule == "cross-thread-state"


def test_module_level_metric_registration_is_visible(tmp_path):
    """A module-scope registration (`M = reg.counter(...)` at import
    time) must satisfy consistency checks and labels() resolution."""
    (tmp_path / "m.py").write_text(
        "import registry as reg\n"
        "REQS = reg.counter('probe_total', 'requests',\n"
        "                   labelnames=('outcome',))\n"
        "OK = REQS.labels(outcome='ok')\n"
        "def record():\n"
        "    REQS.labels(status='bad')\n")  # wrong key: must be caught
    project = model.load_project([str(tmp_path)], str(tmp_path))
    findings = [f for f in core.run_rules(project)
                if f.rule == "metrics-consistency"]
    # the module-level labels(outcome=) call is clean; only the
    # function's labels(status=) mismatches — and registration itself
    # is visible (no 'not registered' style noise)
    assert len(findings) == 1, findings
    assert "status" in findings[0].message


def test_update_baseline_with_no_baseline_keeps_justifications(tmp_path):
    baseline = str(tmp_path / "baseline.txt")
    viol = os.path.join(FIXTURES, "viol_midfile_import.py")
    _lint(viol, "--baseline", baseline, "--update-baseline",
          "--root", FIXTURES)
    text = open(baseline).read().replace("TODO: justify or fix",
                                         "kept on purpose")
    with open(baseline, "w") as f:
        f.write(text)
    # --no-baseline only affects GATING; the rewrite must still merge
    # the existing file's hand-written justifications
    _lint(viol, "--baseline", baseline, "--no-baseline",
          "--update-baseline", "--root", FIXTURES)
    assert "kept on purpose" in open(baseline).read()


# ---- the tree itself ---------------------------------------------------

def test_repo_tree_is_lint_clean():
    """The acceptance invariant verify.sh gates on, asserted in tier-1
    too: the production tree (lstm_tensorspark_tpu/ + tools/) has zero
    findings outside tools/lint_baseline.txt, and every baseline entry
    carries a real justification."""
    project = model.load_project(
        [os.path.join(_REPO, "lstm_tensorspark_tpu"),
         os.path.join(_REPO, "tools")], _REPO)
    findings = core.run_rules(project)
    baseline = core.load_baseline(
        os.path.join(_REPO, "tools", "lint_baseline.txt"))
    new = [f for f in findings if f.key() not in baseline]
    assert new == [], "\n".join(f.render() for f in new)
    for key, justification in baseline.items():
        assert justification and "TODO" not in justification, (
            f"baseline entry {key} lacks a real justification")
