"""DCN-aware hybrid mesh (parallel/mesh.py make_hybrid_mesh).

SURVEY.md §5 comm-backend row: the TPU-native replacement for the
reference's Spark netty layer is ICI collectives within a slice and DCN
between slices. The hybrid mesh encodes the scaling-book placement recipe
— slice-major device order so the data axis varies slices slowest and
every tp*sp*pp block stays inside one interconnect domain. No hardware
multi-slice exists here, so coverage is three-layered: pure-logic tests on
fake device objects (grouping, validation), real-device degeneracy on the
8-device CPU mesh (single domain ⇒ identical to make_mesh), and a REAL
2-process Gloo run asserting placement + DP training parity
(tests/test_multiprocess.py harness).
"""

import dataclasses

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.parallel import (
    make_hybrid_mesh, make_mesh, slice_groups,
)


@dataclasses.dataclass(frozen=True)
class FakeDev:
    id: int
    process_index: int
    slice_index: int | None = None


def test_slice_groups_prefers_slice_index_over_process():
    devs = [FakeDev(id=i, process_index=0, slice_index=i % 2)
            for i in range(4)]
    groups = slice_groups(devs)
    assert [[d.id for d in g] for g in groups] == [[0, 2], [1, 3]]


def test_slice_groups_falls_back_to_process_index():
    devs = [FakeDev(id=3, process_index=1), FakeDev(id=0, process_index=0),
            FakeDev(id=2, process_index=1), FakeDev(id=1, process_index=0)]
    groups = slice_groups(devs)
    assert [[d.id for d in g] for g in groups] == [[0, 1], [2, 3]]


def test_hybrid_mesh_rejects_unequal_domains():
    devs = [FakeDev(id=0, process_index=0), FakeDev(id=1, process_index=0),
            FakeDev(id=2, process_index=1)]
    with pytest.raises(ValueError, match="unequal"):
        make_hybrid_mesh(devices=devs)


def test_hybrid_mesh_rejects_dcn_straddling_model_block():
    # 2 domains x 4 devices, tp=3: block 3 does not divide the domain
    # size 4, so some tp collective would cross DCN
    devs = [FakeDev(id=i, process_index=i // 4) for i in range(8)]
    with pytest.raises(ValueError, match="straddle"):
        make_hybrid_mesh(dp=None, tp=3, devices=devs)
    # a block that SPANS whole domains (tp=8 over two slices of 4) is
    # rejected too — its per-timestep all-gather would ride DCN
    with pytest.raises(ValueError, match="straddle"):
        make_hybrid_mesh(dp=None, tp=8, devices=devs)


def test_hybrid_degenerates_to_plain_mesh_single_domain():
    """On one process (the CPU test mesh) hybrid ordering is exactly the
    plain ordering — same devices, same positions, same axis names."""
    devs = jax.devices()
    hybrid = make_hybrid_mesh(dp=2, tp=2, sp=2, pp=1, devices=devs)
    plain = make_mesh(dp=2, tp=2, sp=2, pp=1, devices=np.asarray(devs))
    assert hybrid.axis_names == plain.axis_names
    assert (hybrid.devices == plain.devices).all()


def test_hybrid_mesh_slice_major_data_axis():
    """With 2 fake domains of 4, dp=2 must map data shard i to domain i
    and keep each tp block inside one domain."""
    devs = [FakeDev(id=i, process_index=(i >= 4)) for i in range(8)]
    # reorder the input to prove sorting does the work
    shuffled = [devs[i] for i in (5, 0, 3, 7, 2, 6, 1, 4)]
    groups = slice_groups(shuffled)
    ordered = [d for g in groups for d in g]
    assert [d.id for d in ordered] == list(range(8))
    arr = np.array(ordered, dtype=object).reshape(2, 4, 1, 1)
    for shard in range(2):
        assert {d.process_index for d in arr[shard].flat} == {shard}


def test_cli_build_mesh_falls_back_only_on_unequal_domains(monkeypatch):
    """_build_mesh: the unequal-domains truncation case falls back to the
    plain ordering; the straddling-model-block case stays a hard error."""
    from lstm_tensorspark_tpu import parallel
    from lstm_tensorspark_tpu.cli import _build_mesh

    # single-domain real devices: just works (hybrid == plain)
    mesh = _build_mesh(dp=4, tp=2, devices=np.asarray(jax.devices()))
    assert mesh.devices.shape == (4, 2, 1, 1)

    # straddle error propagates (fakes: 2 domains of 4, tp=3)
    devs = [FakeDev(id=i, process_index=i // 4) for i in range(8)]
    with pytest.raises(ValueError, match="straddle"):
        _build_mesh(dp=None, tp=3, devices=devs)

    # unequal domains (truncation: 4 + 2 devices) take the fallback —
    # plain make_mesh is reached with the original arguments (real Mesh
    # construction rejects fake devices, so stub it to a sentinel)
    uneven = devs[:6]
    sentinel = object()
    seen = {}

    def fake_make_mesh(dp=None, tp=1, sp=1, pp=1, *, devices=None):
        seen.update(dp=dp, tp=tp, n=len(devices))
        return sentinel

    monkeypatch.setattr(parallel, "make_mesh", fake_make_mesh)
    assert _build_mesh(dp=6, tp=1, devices=uneven) is sentinel
    assert seen == {"dp": 6, "tp": 1, "n": 6}
