"""Unit numerics for the hand-rolled cell (SURVEY.md §4 test pyramid):
fused vs unfused parity, flax.linen.LSTMCell oracle, grad vs finite
differences."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lstm_tensorspark_tpu.ops import (
    init_lstm_params,
    fuse_params,
    lstm_step,
    lstm_step_unfused,
    lstm_scan,
)
from lstm_tensorspark_tpu.ops.lstm_cell import zero_carry

B, D, H, T = 4, 6, 8, 10


@pytest.fixture
def params():
    return init_lstm_params(jax.random.PRNGKey(0), D, H)


def test_shapes(params):
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))
    carry = zero_carry(B, H)
    (h, c), y = lstm_step(fuse_params(params), carry, x)
    assert h.shape == (B, H) and c.shape == (B, H) and y.shape == (B, H)


def test_fused_matches_unfused(params):
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))
    h0 = jax.random.normal(jax.random.PRNGKey(2), (B, H))
    c0 = jax.random.normal(jax.random.PRNGKey(3), (B, H))
    (h1, c1), _ = lstm_step(fuse_params(params), (h0, c0), x)
    (h2, c2), _ = lstm_step_unfused(params, (h0, c0), x)
    np.testing.assert_allclose(h1, h2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(c1, c2, rtol=1e-5, atol=1e-5)


def test_forget_bias(params):
    assert np.allclose(params.b_f, 1.0)
    assert np.allclose(params.b_i, 0.0)


def test_flax_oracle(params):
    """Copy our per-gate params into flax.linen.LSTMCell and compare a step."""
    import flax.linen as nn

    cell = nn.LSTMCell(features=H)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))
    h0 = jax.random.normal(jax.random.PRNGKey(2), (B, H))
    c0 = jax.random.normal(jax.random.PRNGKey(3), (B, H))
    variables = cell.init(jax.random.PRNGKey(4), (c0, h0), x)

    # flax gates: i=ii/hi, f=if/hf, g=ig/hg, o=io/ho; bias lives on h-dense.
    fp = {"params": {}}
    for gate in "ifgo":
        W = getattr(params, f"W_{gate}")
        U = getattr(params, f"U_{gate}")
        b = getattr(params, f"b_{gate}")
        fp["params"][f"i{gate}"] = {"kernel": W}
        fp["params"][f"h{gate}"] = {"kernel": U, "bias": b}
    jax.tree.map(  # structural check against the real flax param tree
        lambda a, b_: None, variables["params"], fp["params"]
    )

    (c1f, h1f), yf = cell.apply(fp, (c0, h0), x)
    (h1, c1), y = lstm_step(fuse_params(params), (h0, c0), x)
    np.testing.assert_allclose(h1, h1f, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(c1, c1f, rtol=1e-5, atol=1e-5)


def test_scan_matches_python_loop(params):
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    (h, c), ys = lstm_scan(params, xs)
    carry = zero_carry(B, H)
    fused = fuse_params(params)
    outs = []
    for t in range(T):
        carry, y = lstm_step(fused, carry, xs[:, t])
        outs.append(y)
    np.testing.assert_allclose(h, carry[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ys, jnp.stack(outs, axis=1), rtol=1e-5, atol=1e-5)


def test_grads_finite_differences(params):
    from jax.test_util import check_grads

    xs = jax.random.normal(jax.random.PRNGKey(1), (2, 5, D))

    def loss(p, xs):
        (h, _), ys = lstm_scan(p, xs)
        return jnp.sum(h**2) + jnp.mean(ys**2)

    check_grads(loss, (params, xs), order=1, modes=["rev"], atol=1e-2, rtol=1e-2)


def test_remat_matches_plain(params):
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, 12, D))

    def loss(p, chunk):
        (h, _), ys = lstm_scan(p, xs, remat_chunk=chunk)
        return jnp.mean(ys**2) + jnp.sum(h)

    l0, g0 = jax.value_and_grad(lambda p: loss(p, None))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(p, 4))(params)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6), g0, g1
    )


def test_mask_freezes_carry(params):
    xs = jax.random.normal(jax.random.PRNGKey(1), (2, 6, D))
    lengths = jnp.array([4, 6])
    from lstm_tensorspark_tpu.ops import sequence_mask

    mask = sequence_mask(lengths, 6)
    (h, c), ys = lstm_scan(params, xs, mask=mask)
    # row 0's final state must equal the state after scanning only 4 steps
    (h4, c4), _ = lstm_scan(params, xs[:1, :4])
    np.testing.assert_allclose(h[0], h4[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(c[0], c4[0], rtol=1e-5, atol=1e-5)
    # outputs after the end hold the frozen state
    np.testing.assert_allclose(ys[0, 3], ys[0, 5], rtol=1e-5, atol=1e-5)


def test_reverse_scan(params):
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    (h, _), ys = lstm_scan(params, xs, reverse=True)
    (h2, _), ys2 = lstm_scan(params, xs[:, ::-1])
    np.testing.assert_allclose(h, h2, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ys, ys2[:, ::-1], rtol=1e-5, atol=1e-5)


def test_bf16_compute_close(params):
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    (h32, _), _ = lstm_scan(params, xs)
    (hbf, _), _ = lstm_scan(params, xs, compute_dtype=jnp.bfloat16)
    assert hbf.dtype == jnp.float32  # accumulation/state stay f32
    np.testing.assert_allclose(h32, hbf, rtol=0.1, atol=0.05)


def test_long_sequence_remat_chunk_grads():
    """T=512 with remat chunking: grads finite and matching the no-remat
    scan (the long-context crux path — SURVEY.md §7 'Hard parts')."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from lstm_tensorspark_tpu.ops import init_lstm_params, lstm_scan

    params = init_lstm_params(jax.random.PRNGKey(0), 8, 16)
    xs = jax.random.normal(jax.random.PRNGKey(1), (2, 512, 8))

    def loss(p, chunk):
        return jnp.mean(lstm_scan(p, xs, remat_chunk=chunk)[1] ** 2)

    g_remat = jax.jit(jax.grad(lambda p: loss(p, 64)))(params)
    g_full = jax.jit(jax.grad(lambda p: loss(p, None)))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-7),
        g_remat, g_full,
    )
