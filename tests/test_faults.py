"""Fault-injection plane (resilience/faults.py): spec parsing, one-shot
marker persistence, batch-feed faults, checkpoint corruption, determinism."""

import os

import pytest

from lstm_tensorspark_tpu.resilience import faults
from lstm_tensorspark_tpu.resilience.exit_codes import FAULT_CRASH_RC


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.disarm()
    yield
    # explicit pop, not monkeypatch: the CLI EXPORTS the var mid-test
    # (--faults -> env for children) and delenv-on-absent records no undo
    os.environ.pop(faults.ENV_VAR, None)
    faults.disarm()


def test_spec_parsing_full_grammar():
    p = faults.FaultPlane(
        "crash@5; nan_grads@3x2; ckpt_corrupt@4; data_error@6;"
        "serve_error@2; seed@7"
    )
    assert p.crash_steps == {5}
    assert p.nan_grad_steps == (3, 4)
    assert p.ckpt_corrupt_steps == {4}
    assert p.data_error_steps == {6}
    assert p.serve_error_calls == {2}
    assert p.seed == 7


@pytest.mark.parametrize("bad", [
    "crash",              # no @N
    "crash@x",            # non-numeric
    "explode@3",          # unknown kind
    "crash@3x2",          # xK suffix invalid for crash
    "disk_write_err@1x2",  # xK suffix invalid for disk faults
])
def test_spec_parse_errors(bad):
    with pytest.raises(ValueError):
        faults.FaultPlane(bad)


def test_serve_spec_parsing():
    p = faults.FaultPlane(
        "replica_die@1x3; replica_wedge@0x5; wedge_secs@7;"
        "disk_write_err@2; disk_read_err@4; session_corrupt@1;"
        "spill_stall@2x3; slow_readback@5x100"
    )
    assert p.replica_die == {1: 3}
    assert p.replica_wedge == {0: 5}
    assert p.wedge_secs == 7
    assert p.disk_write_err_calls == {2}
    assert p.disk_read_err_calls == {4}
    assert p.session_corrupt_writes == {1}
    assert p.spill_stall_batches == {2: 3}
    assert p.slow_readback_calls == {5: 100}
    # defaults: xK omitted
    q = faults.FaultPlane("replica_die@0;spill_stall@1;slow_readback@1")
    assert q.replica_die == {0: 1}
    assert q.spill_stall_batches == {1: 1}
    assert q.slow_readback_calls == {1: 250}


def test_replica_die_hook_fires_on_that_replicas_kth_step():
    p = faults.FaultPlane("replica_die@1x2")
    p.serve_step_hook(0)  # other replica: never fires
    p.serve_step_hook(1)  # replica 1 step 1: not yet
    with pytest.raises(faults.InjectedFault):
        p.serve_step_hook(1)  # replica 1 step 2: dies
    p.serve_step_hook(1)  # past the scheduled step: no re-fire
    p.serve_step_hook(0)


def test_disk_hooks_fire_on_nth_call_only():
    p = faults.FaultPlane("disk_write_err@2;disk_read_err@1")
    p.serve_disk_hook("write")
    with pytest.raises(OSError):
        p.serve_disk_hook("write")
    p.serve_disk_hook("write")  # once only
    with pytest.raises(OSError):
        p.serve_disk_hook("read")
    p.serve_disk_hook("read")


def test_session_corrupt_damages_nth_write(tmp_path):
    p = faults.FaultPlane("session_corrupt@2;seed@3")
    a, b = tmp_path / "a.state", tmp_path / "b.state"
    payload = b'{"sid": "x"}\n' + b"\x01" * 64
    for f in (a, b):
        f.write_bytes(payload)
    p.maybe_corrupt_session(str(a))  # write 1: untouched
    p.maybe_corrupt_session(str(b))  # write 2: truncated + flipped
    assert a.read_bytes() == payload
    damaged = b.read_bytes()
    assert len(damaged) == len(payload) // 2
    assert damaged != payload[: len(damaged)]


def test_arm_from_env(monkeypatch, tmp_path):
    monkeypatch.setenv(faults.ENV_VAR, "crash@9")
    plane = faults.arm_from_env(state_dir=str(tmp_path))
    assert plane is faults.active()
    assert plane.crash_steps == {9}
    monkeypatch.delenv(faults.ENV_VAR)
    faults.disarm()
    assert faults.arm_from_env() is None


def test_one_shot_markers_persist_across_planes(tmp_path):
    """A restarted child (fresh plane, same state_dir) must see a fired
    fault as fired — the crash-loop prevention contract."""
    p1 = faults.FaultPlane("crash@5", state_dir=str(tmp_path))
    assert not p1.fired("crash@5")
    p1.mark_fired("crash@5")
    assert p1.fired("crash@5")
    p2 = faults.FaultPlane("crash@5", state_dir=str(tmp_path))  # "restart"
    assert p2.fired("crash@5")
    assert os.path.exists(tmp_path / ".faults" / "crash@5.fired")


def test_wrap_batches_crash_fires_once(monkeypatch, tmp_path):
    crashes = []
    monkeypatch.setattr(faults, "_crash",
                        lambda: (_ for _ in ()).throw(SystemExit(FAULT_CRASH_RC)))
    plane = faults.FaultPlane("crash@3", state_dir=str(tmp_path))
    out = []
    with pytest.raises(SystemExit) as ei:
        for b in plane.wrap_batches(iter(range(10)), start_step=0):
            out.append(b)
    assert ei.value.code == FAULT_CRASH_RC
    assert out == [0, 1]  # steps 1, 2 ran; crash fired before step 3
    # the "restarted" plane resumes past the marker without re-firing
    plane2 = faults.FaultPlane("crash@3", state_dir=str(tmp_path))
    resumed = list(plane2.wrap_batches(iter(range(2, 10)), start_step=2))
    assert resumed == list(range(2, 10))
    assert not crashes


def test_wrap_batches_data_error():
    plane = faults.FaultPlane("data_error@2")  # in-memory one-shot
    out = []
    with pytest.raises(faults.InjectedFault):
        for b in plane.wrap_batches(iter(range(5)), start_step=0):
            out.append(b)
    assert out == [0]
    # same plane (same process): already fired, passes through
    assert list(plane.wrap_batches(iter(range(5)), start_step=1)) == list(range(5))


def test_wrap_batches_steps_per_call_window():
    """With K steps per dispatch the fault must fire when its step falls
    anywhere inside the next window."""
    plane = faults.FaultPlane("data_error@6")
    out = []
    with pytest.raises(faults.InjectedFault):
        # windows: [1..4], [5..8] — step 6 is inside the second window
        for b in plane.wrap_batches(iter(range(5)), start_step=0,
                                    steps_per_call=4):
            out.append(b)
    assert out == [0]


def test_wrap_batches_resume_coordinates():
    """Step numbering is GLOBAL: a resumed feed starting at step 4 must not
    re-enter the window of a step-3 fault."""
    plane = faults.FaultPlane("data_error@3")
    assert list(plane.wrap_batches(iter(range(5)), start_step=4)) == list(range(5))


def test_maybe_corrupt_checkpoint_truncates_once(tmp_path):
    plane = faults.FaultPlane("ckpt_corrupt@4;seed@1", state_dir=str(tmp_path))
    f = tmp_path / "step_4.msgpack"
    payload = bytes(range(256)) * 4
    f.write_bytes(payload)
    plane.maybe_corrupt_checkpoint(str(f), 4)
    damaged = f.read_bytes()
    assert len(damaged) == len(payload) // 2
    assert damaged != payload[: len(damaged)]  # seeded byte flip applied
    # wrong step: no-op; fired step: no second corruption
    f2 = tmp_path / "step_6.msgpack"
    f2.write_bytes(payload)
    plane.maybe_corrupt_checkpoint(str(f2), 6)
    assert f2.read_bytes() == payload
    f.write_bytes(payload)
    plane.maybe_corrupt_checkpoint(str(f), 4)
    assert f.read_bytes() == payload


def test_serve_hook_fires_on_nth_call():
    plane = faults.arm("serve_error@3")
    faults.serve_decode_hook()
    faults.serve_decode_hook()
    with pytest.raises(faults.InjectedFault):
        faults.serve_decode_hook()
    faults.serve_decode_hook()  # one-shot: call 4 is clean


def test_unarmed_hooks_are_noops(tmp_path):
    faults.serve_decode_hook()
    faults.maybe_corrupt_checkpoint(str(tmp_path / "x"), 1)
    assert faults.tamper_grads({"w": 1.0}, 0) == {"w": 1.0}
