"""Data-layer tests: vocab, LM windowing (target shift), padded batches,
dataset registry (SURVEY.md §4 test pyramid)."""

import contextlib
import os

import numpy as np

from lstm_tensorspark_tpu.data import (
    build_char_vocab,
    build_word_vocab,
    get_dataset,
    lm_epoch_batches,
    padded_batches,
)
from lstm_tensorspark_tpu.data.corpus import synthetic_text


@contextlib.contextmanager
def force_python_native():
    """Disable the native library inside the block (and reset the load
    cache on BOTH edges so neither direction leaks into other tests).
    Restores the operator's own LSTM_TSP_NO_NATIVE value on exit — a bare
    del would re-enable the .so for the rest of a suite run the operator
    launched with the variable set."""
    from lstm_tensorspark_tpu.data import native

    prior = os.environ.get("LSTM_TSP_NO_NATIVE")
    os.environ["LSTM_TSP_NO_NATIVE"] = "1"
    native._load_attempted = False
    native._lib = None
    try:
        yield
    finally:
        if prior is None:
            del os.environ["LSTM_TSP_NO_NATIVE"]
        else:
            os.environ["LSTM_TSP_NO_NATIVE"] = prior
        native._load_attempted = False
        native._lib = None


def test_char_vocab_roundtrip():
    text = "hello world"
    v = build_char_vocab(text)
    ids = v.encode(list(text))
    assert "".join(v.decode(ids)) == text
    assert v.encode(["@"])[0] == v.stoi["<unk>"]


def test_word_vocab_max_size():
    v = build_word_vocab("a a a b b c", max_size=4)
    assert len(v) == 4  # pad, unk, a, b
    assert v.encode(["c"])[0] == v.UNK


def test_lm_windows_shift():
    tokens = np.arange(100, dtype=np.int32)
    batches = list(lm_epoch_batches(tokens, batch_size=2, seq_len=8))
    assert len(batches) >= 2
    b = batches[0]
    assert b["inputs"].shape == (2, 8)
    np.testing.assert_array_equal(b["targets"], b["inputs"] + 1)
    # window t+1 continues where window t left off (stateful contiguity)
    np.testing.assert_array_equal(
        batches[1]["inputs"][:, 0], batches[0]["inputs"][:, -1] + 1
    )


def test_padded_batches():
    seqs = [np.arange(1, n + 1, dtype=np.int32) for n in (3, 7, 5, 9, 2, 6)]
    labels = np.array([0, 1, 0, 1, 0, 1], np.int32)
    out = list(padded_batches(seqs, labels, batch_size=2, max_len=8))
    assert len(out) == 3
    for b in out:
        assert b["tokens"].shape == (2, 8)
        for row in range(2):
            L = b["lengths"][row]
            assert (b["tokens"][row, :L] > 0).all()
            assert (b["tokens"][row, L:] == 0).all()
    # bucketing: lengths within a batch are adjacent in sorted order
    all_lens = [tuple(b["lengths"]) for b in out]
    flat = [l for pair in all_lens for l in pair]
    assert flat == sorted(flat)
    # drop_remainder=False pads with invalid filler rows, never duplicates
    out2 = list(padded_batches(seqs, labels, batch_size=4, max_len=8,
                               drop_remainder=False))
    assert len(out2) == 2
    last = out2[-1]
    assert last["valid"].sum() == 2 and (last["lengths"][~last["valid"]] == 0).all()


def test_synthetic_text_deterministic():
    assert synthetic_text(500, seed=3) == synthetic_text(500, seed=3)
    assert synthetic_text(500, seed=3) != synthetic_text(500, seed=4)


def test_dataset_registry():
    d = get_dataset("ptb_char")
    assert d["synthetic"] and d["train"].dtype == np.int32
    assert len(d["vocab"]) < 100  # char-level
    d2 = get_dataset("imdb", num_examples=50)
    seqs, labels = d2["train"]
    assert len(seqs) == 40 and set(labels) == {0, 1}
    d3 = get_dataset("uci_electricity", length=1000)
    assert d3["train"].shape[1] == d3["num_features"]


def test_native_encode_parity():
    """Native C++ encoders must match the pure-Python paths exactly (and the
    suite still passes if the .so is unavailable — fallback is automatic)."""

    from lstm_tensorspark_tpu.data import native
    from lstm_tensorspark_tpu.data.corpus import synthetic_text

    text = synthetic_text(2000, seed=7)
    cv = build_char_vocab(text)
    want_c = np.asarray([cv.stoi.get(c, 1) for c in text], np.int32)
    np.testing.assert_array_equal(cv.encode_text(text, "char"), want_c)

    wv = build_word_vocab(text)
    want_w = np.asarray([wv.stoi.get(w, 1) for w in text.split()], np.int32)
    got_w = wv.encode_text(text + " zzznotinvocab", "word")
    np.testing.assert_array_equal(got_w[:-1], want_w)
    assert got_w[-1] == wv.stoi["<unk>"]

    # forced-fallback parity
    with force_python_native():
        np.testing.assert_array_equal(cv.encode_text(text, "char"), want_c)
        np.testing.assert_array_equal(wv.encode_text(text, "word"), want_w)


def test_native_non_ascii_falls_back():
    """Non-ASCII text must take the Python path and stay correct."""
    text = "café au lait café   x"  # é + non-breaking space
    cv = build_char_vocab(text)
    got = cv.encode_text(text, "char")
    want = np.asarray([cv.stoi.get(c, 1) for c in text], np.int32)
    np.testing.assert_array_equal(got, want)
    wv = build_word_vocab(text)
    got_w = wv.encode_text(text, "word")
    want_w = np.asarray([wv.stoi.get(w, 1) for w in text.split()], np.int32)
    np.testing.assert_array_equal(got_w, want_w)


def test_native_control_char_whitespace_parity():
    """ASCII control separators \\x1c-\\x1f split identically in C and Python."""
    text = "alpha\x1cbeta\x1d gamma\x1ealpha\x1fbeta alpha"
    wv = build_word_vocab(text)
    got = wv.encode_text(text, "word")
    want = np.asarray([wv.stoi.get(w, 1) for w in text.split()], np.int32)
    np.testing.assert_array_equal(got, want)


def test_literal_special_token_maps_to_unk():
    """A literal '<pad>'/'<unk>' string in raw text maps to unk on BOTH the
    native and fallback word paths (reserved ids unreachable from text)."""

    from lstm_tensorspark_tpu.data import native

    text = "alpha beta alpha <pad> <unk> beta"
    wv = build_word_vocab("alpha beta alpha beta")
    got_native = wv.encode_text(text, "word")
    with force_python_native():
        got_py = wv.encode_text(text, "word")
    np.testing.assert_array_equal(got_native, got_py)
    unk = wv.stoi["<unk>"]
    np.testing.assert_array_equal(got_py[3:5], [unk, unk])


def test_nul_in_vocab_token_falls_back():
    """A NUL byte inside a vocab token would corrupt the native encoder's
    \\0-delimited vocab buffer; such vocabs must take the Python path."""
    text = "a\x00b plain a\x00b word"
    assert text.isascii() and len(text.split()) == 4
    wv = build_word_vocab(text)
    got = wv.encode_text(text, "word")
    want = np.asarray([wv.stoi.get(w, 1) for w in text.split()], np.int32)
    np.testing.assert_array_equal(got, want)


def test_native_vocab_build_parity():
    """C++ most_common_words must equal Counter.most_common exactly,
    including count-tie ordering (first occurrence wins) and max_size."""
    from collections import Counter

    from lstm_tensorspark_tpu.data import native
    from lstm_tensorspark_tpu.data.corpus import synthetic_text

    def oracle(text, max_size=None):
        return [w for w, _ in Counter(text.split()).most_common(max_size)]

    text = synthetic_text(20_000, seed=11)
    assert native.most_common_words(text) == oracle(text)
    assert native.most_common_words(text, 10) == oracle(text, 10)
    # tie-heavy corpus: every word once, order = first occurrence
    tie = "delta alpha charlie bravo"
    assert native.most_common_words(tie) == oracle(tie)
    # non-ASCII falls back, same result
    assert native.most_common_words("café x café") == oracle("café x café")
    # forced fallback parity
    with force_python_native():
        assert native.most_common_words(text, 50) == oracle(text, 50)


def test_native_vocab_edge_cases():
    """NUL-containing tokens and non-positive max_size must match the
    Counter oracle on both paths (review regressions)."""
    from collections import Counter

    from lstm_tensorspark_tpu.data import native

    def oracle(text, max_size=None):
        return [w for w, _ in Counter(text.split()).most_common(max_size)]

    nul = "a\0b a\0b x"
    assert native.most_common_words(nul) == oracle(nul)  # ['a\0b', 'x']
    assert native.most_common_words("aa bb aa cc", -1) == []
    assert native.most_common_words("aa bb", 0) == []
    assert build_word_vocab("aa bb aa", 1).itos == ["<pad>", "<unk>"]


def test_imdb_real_loader(tmp_path):
    """aclImdb directory layout → encoded sequences with correct labels,
    clip-to-max-len, balanced valid split, synthetic=False."""
    root = tmp_path / "aclImdb"
    docs = {
        ("train", "pos"): ["great movie loved it", "wonderful film great acting"],
        ("train", "neg"): ["terrible movie hated it", "awful film bad acting"],
        ("test", "pos"): ["great " * 500],  # longer than max_len → clipped
        ("test", "neg"): ["bad film"],
    }
    for (split, label), texts in docs.items():
        d = root / split / label
        d.mkdir(parents=True)
        for i, t in enumerate(texts):
            (d / f"{i}_7.txt").write_text(t)
    ds = get_dataset("imdb", str(tmp_path), max_len=16)
    assert ds["synthetic"] is False
    assert ds["num_classes"] == 2
    tr_seqs, tr_labels = ds["train"]
    te_seqs, te_labels = ds["test"]
    assert len(tr_seqs) + len(ds["valid"][0]) == 4
    assert sorted(te_labels.tolist()) == [0, 1]
    assert all(len(s) <= 16 for s in te_seqs)  # clipped
    # vocab built from train split: 'great' must be known, encoded != unk
    v = ds["vocab"]
    assert v.encode(["great"])[0] != v.UNK
    # pointing at the aclImdb dir itself works too
    ds2 = get_dataset("imdb", str(root), max_len=16)
    assert ds2["synthetic"] is False


def test_uci_real_loader(tmp_path):
    """LD2011_2014.txt semicolon CSV with decimal commas → normalised
    [length, num_series] float array, time-ordered 80/10/10 split."""
    lines = ['"";"MT_001";"MT_002";"MT_003"']
    for i in range(100):
        lines.append(
            f'"2011-01-01 {i:02d}:00:00";{i},5;{2 * i},25;{3 * i},0'
        )
    f = tmp_path / "LD2011_2014.txt"
    f.write_text("\n".join(lines) + "\n")
    ds = get_dataset("uci_electricity", str(tmp_path), num_series=2)
    assert ds["synthetic"] is False
    assert ds["num_features"] == 2  # capped at requested num_series
    assert ds["train"].shape == (80, 2)
    assert ds["valid"].shape == (10, 2)
    assert ds["test"].shape == (10, 2)
    # per-series normalisation uses TRAIN-split stats only (no test leakage)
    assert abs(ds["train"].mean()) < 1e-5
    assert abs(ds["train"].std() - 1.0) < 1e-2
    # decimal commas parsed: strictly increasing first column
    full = np.concatenate([ds["train"], ds["valid"], ds["test"]])
    assert (np.diff(full[:, 0]) > 0).all()
    # the file path itself is accepted too
    ds2 = get_dataset("uci_electricity", str(f), num_series=2)
    assert ds2["train"].shape == (80, 2)


def test_native_csv_decimal_comma_parity(tmp_path):
    """The C++ CSV kernel and the pure-Python loop must produce
    byte-identical arrays on the LD2011_2014 format, including the edge
    rows: empty values (-> 0.0), CRLF line ends, short rows (skipped),
    scientific notation, and signs."""

    from lstm_tensorspark_tpu.data import native
    from lstm_tensorspark_tpu.data.datasets import _uci_real

    lines = ['"";"MT_001";"MT_002"']
    rows = ['"t0";1,5;-2,25', '"t1";;3,0', '"t2";1e-3;+4,125',
            '"t3";0;0,0', '"t4-short";7,0', '"t5";  8,5  ;9']
    f = tmp_path / "LD2011_2014.txt"
    # mixed \n and \r\n endings
    f.write_bytes(("\n".join(lines + rows[:3]) + "\r\n"
                   + "\r\n".join(rows[3:]) + "\n").encode())

    if not native.available():
        import pytest
        pytest.skip("native library unavailable")
    got = _uci_real(str(f), num_series=2)

    with force_python_native():
        want = _uci_real(str(f), num_series=2)

    for k in ("train", "valid", "test"):
        np.testing.assert_array_equal(got[k], want[k])
    # 5 data rows survive (the short row is skipped on both paths)
    total = sum(len(got[k]) for k in ("train", "valid", "test"))
    assert total == 5


def test_native_csv_crlf_empty_last_field_stays_native(tmp_path):
    """ADVICE r3: a CRLF row whose LAST field is empty ('...;\\r\\n') must
    parse natively as 0.0 — previously the '\\r' landed inside the field,
    the kernel returned -2, and the entire file silently re-parsed on the
    slow Python path."""
    import pytest

    from lstm_tensorspark_tpu.data import native

    if not native.available():
        pytest.skip("native library unavailable")
    body = b'"t0";1,5;\r\n"t1";2,5;3,0\r\n'
    got = native.parse_decimal_comma_csv(body, 2)
    assert got is not None, "CRLF empty-last-field row fell off the fast path"
    np.testing.assert_array_equal(
        got, np.array([[1.5, 0.0], [2.5, 3.0]], np.float32))


def test_native_csv_lone_cr_universal_newline_parity(tmp_path):
    """ADVICE r3: a LONE '\\r' is a line terminator in the Python
    fallback's text-mode read; the kernel must see the same row structure
    so load behavior doesn't depend on whether the .so is present."""
    import pytest

    from lstm_tensorspark_tpu.data import native
    from lstm_tensorspark_tpu.data.datasets import _uci_real

    if not native.available():
        pytest.skip("native library unavailable")
    # direct kernel check: '\r' splits rows exactly like '\n' and '\r\n'
    body = b'"t0";1,5;2,0\r"t1";3,5;4,0\n"t2";5,5;6,0\r\n"t3";7,5;8,0'
    got = native.parse_decimal_comma_csv(body, 2)
    assert got is not None
    np.testing.assert_array_equal(
        got,
        np.array([[1.5, 2.0], [3.5, 4.0], [5.5, 6.0], [7.5, 8.0]],
                 np.float32))

    # end-to-end parity through the loader, mixed terminators incl. the
    # ADVICE example shape (stray '\r' creating an extra short row)
    header = '"";"MT_001";"MT_002"'
    rows = ['"t0";1,5;2,0', '"t1";3,5;4,0', '"t2";5,5;6,0',
            '"t3";7,5;8,0', '"t4";9,5;10,0']
    f = tmp_path / "LD2011_2014.txt"
    f.write_bytes((header + "\n" + rows[0] + "\r" + rows[1] + "\r\n"
                   + rows[2] + "\r" + "\r" + rows[3] + "\n"
                   + rows[4] + "\r").encode())
    got = _uci_real(str(f), num_series=2)
    with force_python_native():
        want = _uci_real(str(f), num_series=2)
    for k in ("train", "valid", "test"):
        np.testing.assert_array_equal(got[k], want[k])
    assert sum(len(got[k]) for k in ("train", "valid", "test")) == 5


def test_native_csv_garbage_falls_back_to_python_error(tmp_path):
    """A value float() would reject makes the C kernel return -2; the
    loader falls back to the pure loop, which raises the SAME ValueError
    it always raised — the native path never changes error semantics."""
    import pytest

    from lstm_tensorspark_tpu.data.datasets import _uci_real

    f = tmp_path / "LD2011_2014.txt"
    f.write_text('"";"MT_001"\n"t0";not_a_number\n')
    with pytest.raises(ValueError):
        _uci_real(str(f), num_series=1)


def test_native_csv_python_grammar_divergences_fall_back(tmp_path):
    """Fields where strtod and Python float() disagree must take the -2
    fallback: whitespace-only (Python raises), hex floats, nan(chars)."""
    import pytest

    from lstm_tensorspark_tpu.data import native
    from lstm_tensorspark_tpu.data.datasets import _uci_real

    if not native.available():
        pytest.skip("native library unavailable")
    for bad in ("   ", "0x10", "nan(7)"):
        f = tmp_path / "LD2011_2014.txt"
        f.write_text(f'"";"MT_001"\n"t0";{bad}\n"t1";1,5\n')
        with pytest.raises(ValueError):
            _uci_real(str(f), num_series=1)


def test_native_csv_randomized_parity_sweep(tmp_path):
    """Randomized property sweep: random row counts, column counts, value
    formats (decimal comma, scientific, signs, empty fields, short rows,
    CRLF) — the native parse must be byte-identical to the Python loop on
    every sample."""
    import pytest

    from lstm_tensorspark_tpu.data import native
    from lstm_tensorspark_tpu.data.datasets import _uci_real

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.RandomState(11)
    for case in range(8):
        cols = int(rng.randint(1, 6))
        n = int(rng.randint(12, 40))
        lines = [";".join(['""'] + [f'"MT_{i}"' for i in range(cols)])]
        for r in range(n):
            fields = []
            for c in range(cols):
                style = rng.randint(0, 5)
                v = float(rng.randn() * 10 ** rng.randint(-3, 4))
                if style == 0:
                    fields.append(f"{v:.6f}".replace(".", ","))
                elif style == 1:
                    fields.append(f"{v:.3e}".replace(".", ","))
                elif style == 2:
                    fields.append("")  # empty -> 0.0
                elif style == 3:
                    fields.append(f"+{abs(v):.2f}".replace(".", ","))
                else:
                    fields.append(f"{int(v)}")
            row = f'"t{r}";' + ";".join(fields)
            if rng.rand() < 0.1:
                row = row.rsplit(";", 1)[0]  # short row: skipped
            lines.append(row)
        end = "\r\n" if case % 2 else "\n"
        f = tmp_path / "LD2011_2014.txt"
        f.write_bytes((end.join(lines) + end).encode())

        got = _uci_real(str(f), num_series=cols)
        with force_python_native():
            want = _uci_real(str(f), num_series=cols)
        for k in ("train", "valid", "test"):
            np.testing.assert_array_equal(got[k], want[k], err_msg=f"case {case}")


def test_uci_cr_only_line_endings_still_load(tmp_path):
    """Classic-Mac CR-only files loaded via the text-mode loop's universal
    newlines before the native kernel existed; the header sniff and the
    native skip-path must preserve that (the kernel sees no \\n, parses 0
    rows, and the text fallback handles the file as it always did)."""
    from lstm_tensorspark_tpu.data.datasets import _uci_real

    lines = ['"";"MT_001";"MT_002"'] + [
        f'"t{i}";{i},5;{2 * i},25' for i in range(20)]
    f = tmp_path / "LD2011_2014.txt"
    f.write_bytes("\r".join(lines).encode() + b"\r")
    ds = _uci_real(str(f), num_series=5)
    assert ds["num_features"] == 2  # header sniff saw 2 columns, not 40+
    assert len(ds["train"]) == 16


def test_uci_mixed_line_endings_native_parity(tmp_path):
    """A \\r-terminated header with \\n-terminated body rows: the native
    header skip must stop at the FIRST terminator (a binary readline would
    eat the header AND the first data row) — native == fallback."""
    import pytest

    from lstm_tensorspark_tpu.data import native
    from lstm_tensorspark_tpu.data.datasets import _uci_real

    if not native.available():
        pytest.skip("native library unavailable")
    rows = "\n".join(f'"t{i}";{i},5;{2 * i},0' for i in range(20))
    f = tmp_path / "LD2011_2014.txt"
    f.write_bytes(('"";"MT_001";"MT_002"\r' + rows + "\n").encode())
    got = _uci_real(str(f), num_series=2)
    with force_python_native():
        want = _uci_real(str(f), num_series=2)
    for k in ("train", "valid", "test"):
        np.testing.assert_array_equal(got[k], want[k])
    assert sum(len(got[k]) for k in ("train", "valid", "test")) == 20


def test_synthetic_word_corpus_properties():
    """Controlled-entropy stand-in (VERDICT r3 weak 2): deterministic,
    full vocabulary coverage, and the bigram structure is REAL — the
    empirical successor distribution of a word is far from uniform."""
    from lstm_tensorspark_tpu.data.corpus import synthetic_word_corpus

    a = synthetic_word_corpus(20_000, 200, seed=3, noise=0.05)
    b = synthetic_word_corpus(20_000, 200, seed=3, noise=0.05)
    assert a == b  # deterministic
    toks = a.split()
    assert len(toks) == 20_000
    assert len(set(toks)) > 150  # Zipf tail still mostly covered

    # successor concentration: for a frequent word, the top successor
    # should carry a large share (geometric bias p=0.5 -> ~0.5)
    from collections import Counter

    common = Counter(toks).most_common(1)[0][0]
    nxt = Counter(b for x, b in zip(toks[:-1], toks[1:]) if x == common)
    top_share = nxt.most_common(1)[0][1] / sum(nxt.values())
    assert top_share > 0.3, top_share


def test_imdb_synthetic_signal_knob():
    """The SNR knob changes per-example evidence: at signal=1.0 the two
    class vocabularies are disjoint (parity split), at low signal most
    tokens are shared noise."""
    from lstm_tensorspark_tpu.data.datasets import imdb

    hi = imdb(num_examples=100, max_len=60, signal=1.0)
    seqs, labels = hi["train"]
    for seq, lab in zip(seqs[:20], labels[:20]):
        parities = set(int(t) % 2 for t in seq)
        assert parities == {0 if lab else 1}

    lo = imdb(num_examples=100, max_len=60, signal=0.1)
    seqs, labels = lo["train"]
    mixed = sum(
        len(set(int(t) % 2 for t in seq)) == 2 for seq in seqs[:20])
    assert mixed >= 18  # shared-noise tokens dominate both parities


def test_corpus_cache_namespaced_by_version_and_age_gated(tmp_path, monkeypatch):
    """The synthetic word-corpus cache lives under a per-_CORPUS_FMT
    subdirectory, and the stale sweep only deletes OTHER versions' entries
    once old — two concurrently-live checkouts no longer thrash each
    other's caches (ADVICE r5 finding 3)."""
    import tempfile
    import time

    from lstm_tensorspark_tpu.data import datasets

    monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))
    root = tmp_path / "lstm_tsp_corpus_cache"
    # a "foreign version" checkout's cache: one fresh entry, one ancient
    foreign = root / "v0"
    foreign.mkdir(parents=True)
    fresh = foreign / "words_10_5_0_0.0.txt"
    fresh.write_text("x " * 10)
    old = foreign / "words_99_5_0_0.0.txt"
    old.write_text("y " * 99)
    ancient = time.time() - datasets._CACHE_STALE_AGE_S - 60
    os.utime(old, (ancient, ancient))
    # legacy pre-namespace flat file, also ancient
    legacy = root / "words_v0_7_5_0_0.05.txt"
    legacy.write_text("z " * 7)
    os.utime(legacy, (ancient, ancient))

    def gen(n, v, seed, noise):
        return " ".join(str(i % v) for i in range(n))

    out = datasets._cached_word_stream(12, 5, 0, 0.05, gen)
    assert len(out) == 12
    # entry cached under the CURRENT version's namespace
    cached = root / datasets._CORPUS_FMT / "words_12_5_0_0.05.txt"
    assert cached.is_file()
    # cache hit: identical result without regenerating
    assert datasets._cached_word_stream(12, 5, 0, 0.05, gen) == out
    # the foreign version's FRESH entry survived; only the old ones went
    assert fresh.is_file()
    assert not old.exists()
    assert not legacy.exists()
