"""ZeRO-1 optimizer-state sharding (parallel/zero.py) vs plain DP.

The law: the sliced-raveled update IS the leaf-wise update for elementwise
transforms, so a ZeRO-1 run must reproduce the replicated DP trajectory to
float-reassociation — while storing only 1/dp of the moments per shard.
Global-norm clipping is the non-elementwise case and is handled from the
psum'd norm; its parity against optax's in-chain clip is pinned separately.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_dp_train_step, make_mesh
from lstm_tensorspark_tpu.parallel.data_parallel import replicate, shard_batch
from lstm_tensorspark_tpu.parallel.zero import (
    make_zero1_opt_init,
    make_zero1_train_step,
)
from lstm_tensorspark_tpu.train import make_optimizer
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 23, 16, 16, 12


def _setup(opt_name, lr, **opt_kw):
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
    params = init_lm(jax.random.PRNGKey(0), cfg)

    def loss_fn(p, b, r):
        return lm_loss(p, b, cfg)

    opt = make_optimizer(opt_name, lr, **opt_kw)
    mesh = make_mesh(dp=8)
    rng = np.random.RandomState(0)

    def batches(k):
        for _ in range(k):
            yield {
                "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
                "targets": rng.randint(0, V, (B, T)).astype(np.int32),
            }

    return params, loss_fn, opt, mesh, batches


def _run_dp(params, loss_fn, opt, mesh, batches):
    step = make_dp_train_step(loss_fn, opt, mesh)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    state = state._replace(params=replicate(state.params, mesh),
                           opt_state=replicate(state.opt_state, mesh))
    losses = []
    for b in batches:
        state, m = step(state, shard_batch(b, mesh))
        losses.append(float(m["loss"]))
    return state, losses


def _run_zero1(params, loss_fn, opt, mesh, batches, *, clip_norm=None):
    step = make_zero1_train_step(loss_fn, opt, mesh, clip_norm=clip_norm)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    state = state._replace(
        params=replicate(state.params, mesh),
        opt_state=make_zero1_opt_init(opt, mesh)(
            replicate(params, mesh)),
    )
    losses = []
    for b in batches:
        state, m = step(state, shard_batch(b, mesh))
        losses.append(float(m["loss"]))
    return state, losses


@pytest.mark.parametrize("opt_name,lr", [("sgd", 0.5), ("adam", 1e-2)])
def test_zero1_matches_dp_trajectory(opt_name, lr):
    params, loss_fn, opt, mesh, batches = _setup(opt_name, lr)
    s_dp, l_dp = _run_dp(params, loss_fn, opt, mesh, list(batches(5)))

    params2, loss_fn2, opt2, mesh2, batches2 = _setup(opt_name, lr)
    s_z, l_z = _run_zero1(params2, loss_fn2, opt2, mesh2, list(batches2(5)))

    np.testing.assert_allclose(l_z, l_dp, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(s_z.params), jax.device_get(s_dp.params),
    )


def test_zero1_clip_matches_optax_chain_clip():
    """ZeRO-1's psum-norm clipping == optax.clip_by_global_norm in the DP
    chain, at a learning rate/scale where clipping actually engages."""
    clip = 0.05  # global grad norm at init is well above this
    params, loss_fn, opt_clip, mesh, batches = _setup(
        "sgd", 0.5, clip_norm=clip)
    s_dp, l_dp = _run_dp(params, loss_fn, opt_clip, mesh, list(batches(4)))

    params2, loss_fn2, _, mesh2, batches2 = _setup("sgd", 0.5)
    opt_noclip = make_optimizer("sgd", 0.5)  # clip handled by zero1
    s_z, l_z = _run_zero1(params2, loss_fn2, opt_noclip, mesh2,
                          list(batches2(4)), clip_norm=clip)

    np.testing.assert_allclose(l_z, l_dp, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(s_z.params), jax.device_get(s_dp.params),
    )


def test_zero1_opt_state_is_sharded_one_over_dp():
    """Adam moments live 1/dp per shard: the global vector leaves have the
    padded flat length and are sharded P(\"data\"); plain DP replicates the
    full pytree on every shard."""
    params, _, opt, mesh, _ = _setup("adam", 1e-2)
    opt_state = make_zero1_opt_init(opt, mesh)(replicate(params, mesh))

    n = sum(int(np.size(a)) for a in jax.tree.leaves(params))
    dp = mesh.shape["data"]
    chunk = -(-n // dp)

    vec_leaves = [a for a in jax.tree.leaves(opt_state)
                  if getattr(a, "ndim", 0) == 1]
    assert vec_leaves, "adam state should contain mu/nu vectors"
    for leaf in vec_leaves:
        assert leaf.shape == (dp * chunk,)
        # each process-local shard holds chunk elements, not dp*chunk
        shard_shapes = {s.data.shape for s in leaf.addressable_shards}
        assert shard_shapes == {(chunk,)}


@pytest.mark.parametrize("n_extra", [0, 1, 7])
def test_zero1_padding_edges(n_extra):
    """The raveled length may or may not divide dp: exercise exact-divide
    (pad=0) and maximal-pad layouts with a tiny synthetic param pytree and
    assert trajectory parity with plain DP."""
    dp = 8
    mesh = make_mesh(dp=dp)
    # base 16*dp params + n_extra => pad = (-n_extra) % dp
    sizes = [16 * dp, n_extra] if n_extra else [16 * dp]
    keys = jax.random.split(jax.random.PRNGKey(7), len(sizes))
    params = {f"w{i}": jax.random.normal(k, (s,), jnp.float32)
              for i, (s, k) in enumerate(zip(sizes, keys))}

    xs = jax.random.normal(jax.random.PRNGKey(8), (B, sum(sizes)), jnp.float32)

    def loss_fn(p, batch, r):
        flat = jnp.concatenate([p[k] for k in sorted(p)])
        pred = batch @ flat
        return jnp.mean(pred ** 2), {"loss": None, "carries": None}

    opt = make_optimizer("adam", 1e-2)
    batches = [xs] * 3

    s_dp, _ = _run_dp(params, loss_fn, opt, mesh, batches)
    s_z, _ = _run_zero1(params, loss_fn, opt, mesh, batches)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(s_z.params), jax.device_get(s_dp.params),
    )


def test_zero1_multistep_matches_single_dispatch():
    """K-step ZeRO-1 (scan inside the shard_map) == K single dispatches:
    same final params, and the summarized metrics follow the multi-step
    contract (mean loss over K, final grad_norm)."""
    params, loss_fn, opt, mesh, batches = _setup("adam", 1e-2)
    bs = list(batches(4))

    s_one, l_one = _run_zero1(params, loss_fn, opt, mesh, bs)

    step_k = make_zero1_train_step(loss_fn, opt, mesh, steps_per_call=4)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    state = state._replace(
        params=replicate(state.params, mesh),
        opt_state=make_zero1_opt_init(opt, mesh)(replicate(params, mesh)),
    )
    stacked = jax.tree.map(lambda *a: np.stack(a), *bs)
    state, m = step_k(state, shard_batch(stacked, mesh, dim=1))

    np.testing.assert_allclose(float(m["loss"]), np.mean(l_one),
                               rtol=1e-5, atol=1e-6)
    assert "loss_last" in m
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6),
        jax.device_get(state.params), jax.device_get(s_one.params),
    )
    assert int(jax.device_get(state.step)) == 4


# ---------------------------------------------------------------------------
# GSPMD ZeRO-1 x tensor parallelism (zero1_tp_opt_specs): the TP task
# runners' form — moment leaves sharded over data AND model, trajectory
# identical to the plain TP step, no clip special-casing.
# ---------------------------------------------------------------------------


def _tp_setup(zero1: bool, *, clip=None):
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from lstm_tensorspark_tpu.models import (
        ClassifierConfig, classifier_loss, init_classifier,
    )
    from lstm_tensorspark_tpu.parallel.tensor_parallel import (
        classifier_param_specs, make_tp_train_step, place_params,
    )
    from lstm_tensorspark_tpu.parallel.zero import zero1_tp_opt_specs

    cfg = ClassifierConfig(vocab_size=V, hidden_size=H, num_layers=1)
    params = init_classifier(jax.random.PRNGKey(0), cfg)
    opt = make_optimizer("adam", 1e-2, clip_norm=clip)
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                ("data", "model"))
    specs = classifier_param_specs(params)

    def loss_fn(p, b, r):
        return classifier_loss(p, b, cfg)

    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    state = state._replace(params=place_params(state.params, specs, mesh))
    opt_specs = None
    if zero1:
        opt_specs = zero1_tp_opt_specs(opt, params, specs, mesh)
        state = state._replace(
            opt_state=place_params(state.opt_state, opt_specs, mesh))
    step = make_tp_train_step(loss_fn, opt, mesh, params, param_specs=specs,
                              opt_state_specs=opt_specs)
    rng = np.random.RandomState(7)

    def batches(k):
        for _ in range(k):
            yield {
                "tokens": rng.randint(0, V, (B, T)).astype(np.int32),
                "lengths": np.full((B,), T, np.int32),
                "labels": rng.randint(0, 2, (B,)).astype(np.int32),
                "valid": np.ones((B,), np.float32),
            }

    return state, step, batches, opt_specs


@pytest.mark.parametrize("clip", [None, 0.5])
def test_zero1_tp_matches_plain_tp_trajectory(clip):
    """Same batches, same seed: the data-sharded-moments step must walk the
    exact trajectory of the propagation-sharded step — the annotation moves
    MEMORY, not math. Clipping needs no special casing here (grads are
    logically replicated over data), so it rides along unchanged."""
    out = {}
    for zero1 in (False, True):
        state, step, batches, _ = _tp_setup(zero1, clip=clip)
        losses = []
        for b in batches(5):
            state, m = step(state, b)
            losses.append(float(m["loss"]))
        out[zero1] = (losses, state)
    np.testing.assert_allclose(out[True][0], out[False][0], rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        out[True][1].params, out[False][1].params,
    )


def test_zero1_tp_moments_shard_over_data_and_model():
    """The published memory claim: after a step, every matrix moment leaf
    is sharded over BOTH axes (1/(dp*tp) per device), and the output state
    PRESERVES it (the out_shardings pin — propagation alone would undo it)."""
    from jax.sharding import PartitionSpec as P

    from jax.tree_util import GetAttrKey, tree_flatten_with_path

    state, step, batches, opt_specs = _tp_setup(True)
    for b in batches(2):
        state, _ = step(state, b)
    leaves = tree_flatten_with_path(state.opt_state)[0]
    mats = [a for path, a in leaves
            if GetAttrKey("mu") in path and a.ndim == 2]
    assert mats, "expected matrix moment leaves under .mu"
    both = 0
    for a in mats:
        spec = a.sharding.spec
        # every matrix moment picks up the data axis; the TP-sharded cell
        # kernels keep the model axis too -> 1/(dp*tp) per device
        assert "data" in spec, spec
        if "model" in spec:
            both += 1
            shard = a.addressable_shards[0].data
            assert shard.size * 4 == a.size, (shard.shape, a.shape)
    assert both >= 16, f"cell kernels should shard over both axes ({both})"
    # scalar leaves (adam's count) stay replicated
    counts = [a for path, a in leaves
              if GetAttrKey("count") in path]
    assert counts and all(c.sharding.spec == P() for c in counts)


def test_zero1_tp_specs_suffix_matching_is_shape_guarded():
    """Path-suffix matching must not mis-bind a moment leaf whose suffix
    matches a param path with a DIFFERENT shape; unmatched/scalar leaves
    stay replicated."""
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    from lstm_tensorspark_tpu.parallel.zero import zero1_tp_opt_specs

    params = {"a": {"b": jnp.zeros((8, 8))}, "b": jnp.zeros((4,))}
    specs = {"a": {"b": P(None, "model")}, "b": P()}
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                ("data", "model"))
    out = zero1_tp_opt_specs(optax.adam(1e-3), params, specs, mesh)
    mu = out[0].mu
    # ['a']['b'] ends with ('b',) too, but shape 8x8 != (4,): the longer
    # exact match must win and carry the model axis forward
    assert mu["a"]["b"] == P("data", "model")
    assert mu["b"] == P("data")
    assert out[0].count == P()


def test_zero1_tp_specs_reject_malformed_inputs():
    """Hardening: a spec tree with the wrong leaf count must error (zip
    would silently mispair), and an optimizer whose state mirrors nothing
    (factored accumulators) must refuse rather than pin everything
    replicated — which would use MORE memory than plain propagation."""
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    from lstm_tensorspark_tpu.parallel.zero import zero1_tp_opt_specs

    params = {"a": jnp.zeros((8, 8)), "b": jnp.zeros((4,))}
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                ("data", "model"))
    with pytest.raises(ValueError, match="mirror"):
        zero1_tp_opt_specs(optax.adam(1e-3), params, {"a": P()}, mesh)
    # same leaf COUNT but a typoed key: positional zip would mispair
    # silently; the path-keyed pairing must refuse
    with pytest.raises(ValueError, match="mirror"):
        zero1_tp_opt_specs(optax.adam(1e-3), params,
                           {"a": P(None, "model"), "z": P()}, mesh)
    specs = {"a": P(None, "model"), "b": P()}
    # a factored-accumulator-style state (nothing mirrors the params):
    # refusal, not a silent all-replicated pin
    factored = optax.GradientTransformation(
        init=lambda p: {"acc": jnp.zeros((3,))},
        update=lambda g, s, p=None: (g, s),
    )
    with pytest.raises(ValueError, match="mirrors the params"):
        zero1_tp_opt_specs(factored, params, specs, mesh)


def test_zero1_tp_checkpoint_reshards_across_mesh_shapes(tmp_path):
    """The docs claim GSPMD ZeRO-1 checkpoints reshard across ANY later
    dp x tp (full logical shapes — unlike the ravel form's padded-flat
    contract). Back it: train on dp2 x tp2, checkpoint, restore onto a
    dp4 x tp1 mesh AND onto a plain single-device state; continuing on
    either must match the uninterrupted dp2 x tp2 run step-for-step."""
    import numpy as np
    from jax.sharding import Mesh

    from lstm_tensorspark_tpu.models import (
        ClassifierConfig, classifier_loss, init_classifier,
    )
    from lstm_tensorspark_tpu.parallel.tensor_parallel import (
        classifier_param_specs, make_tp_train_step, place_params,
    )
    from lstm_tensorspark_tpu.parallel.zero import zero1_tp_opt_specs
    from lstm_tensorspark_tpu.train import make_train_step
    from lstm_tensorspark_tpu.train.checkpoint import Checkpointer

    cfg = ClassifierConfig(vocab_size=V, hidden_size=H, num_layers=1)
    params = init_classifier(jax.random.PRNGKey(0), cfg)
    opt = make_optimizer("adam", 1e-2)
    specs = classifier_param_specs(params)

    rng = np.random.RandomState(3)
    bs = [{
        "tokens": rng.randint(0, V, (B, T)).astype(np.int32),
        "lengths": np.full((B,), T, np.int32),
        "labels": rng.randint(0, 2, (B,)).astype(np.int32),
        "valid": np.ones((B,), np.float32),
    } for _ in range(4)]

    def build(mesh_shape):
        mesh = Mesh(np.asarray(jax.devices()[: np.prod(mesh_shape)])
                    .reshape(mesh_shape), ("data", "model"))
        opt_specs = zero1_tp_opt_specs(opt, params, specs, mesh)
        step = make_tp_train_step(
            lambda p, b, r: classifier_loss(p, b, cfg), opt, mesh, params,
            param_specs=specs, opt_state_specs=opt_specs, donate=False)
        st = init_train_state(params, opt, jax.random.PRNGKey(1))
        return mesh, opt_specs, step, st._replace(
            params=place_params(st.params, specs, mesh),
            opt_state=place_params(st.opt_state, opt_specs, mesh))

    # uninterrupted dp2 x tp2 reference over all 4 batches
    _, _, step_a, st = build((2, 2))
    ref = st
    losses_ref = []
    for b in bs:
        ref, m = step_a(ref, b)
        losses_ref.append(float(m["loss"]))

    # train 2 steps, checkpoint the SHARDED state (st is untouched by the
    # functional reference loop above — no second build needed)
    st2 = st
    for b in bs[:2]:
        st2, _ = step_a(st2, b)
    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(st2)

    # (a) restore onto dp4 x tp1 and continue there
    mesh_b, opt_specs_b, step_b, fresh_b = build((4, 1))
    restored = ckpt.restore_latest(fresh_b)
    restored = restored._replace(
        params=place_params(restored.params, specs, mesh_b),
        opt_state=place_params(restored.opt_state, opt_specs_b, mesh_b))
    out_b = []
    for b in bs[2:]:
        restored, m = step_b(restored, b)
        out_b.append(float(m["loss"]))
    np.testing.assert_allclose(out_b, losses_ref[2:], rtol=1e-5, atol=1e-6)

    # (b) restore onto a plain unsharded single-device state and continue
    fresh_c = init_train_state(params, opt, jax.random.PRNGKey(1))
    restored_c = ckpt.restore_latest(fresh_c)
    step_c = make_train_step(
        lambda p, b, r: classifier_loss(p, b, cfg), opt)
    out_c = []
    for b in bs[2:]:
        restored_c, m = step_c(restored_c, b)
        out_c.append(float(m["loss"]))
    np.testing.assert_allclose(out_c, losses_ref[2:], rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("model", ["seq2seq", "lm"])
def test_zero1_tp_other_model_families(model):
    """The spec derivation is model-agnostic: the seq2seq tree (encoder and
    decoder layer-0 cells have IDENTICAL shapes at different paths — the
    full-path-suffix match must keep them apart) and the LM via the
    library-level GSPMD TP step (the CLI's LM TP is the manual {data,seq}
    form and rejects --zero1, but make_tp_train_step's default
    lm_param_specs composes fine). Trajectory must match the plain TP step."""
    from jax.sharding import Mesh

    from lstm_tensorspark_tpu.parallel.tensor_parallel import (
        lm_param_specs, make_tp_train_step, place_params,
        seq2seq_param_specs,
    )
    from lstm_tensorspark_tpu.parallel.zero import zero1_tp_opt_specs

    rng = np.random.RandomState(5)
    if model == "seq2seq":
        from lstm_tensorspark_tpu.models import (
            Seq2SeqConfig, init_seq2seq, seq2seq_loss,
        )

        cfg = Seq2SeqConfig(num_features=6, hidden_size=H, num_layers=2,
                            horizon=4)
        params = init_seq2seq(jax.random.PRNGKey(0), cfg)
        specs = seq2seq_param_specs(params)
        loss = lambda p, b, r: seq2seq_loss(p, b, cfg)  # noqa: E731
        batches = [{
            "context": rng.randn(B, 10, 6).astype(np.float32),
            "targets": rng.randn(B, 4, 6).astype(np.float32),
        } for _ in range(4)]
    else:
        cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
        params = init_lm(jax.random.PRNGKey(0), cfg)
        specs = lm_param_specs(params)
        loss = lambda p, b, r: lm_loss(p, b, cfg)  # noqa: E731
        batches = [{
            "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
            "targets": rng.randint(0, V, (B, T)).astype(np.int32),
        } for _ in range(4)]

    opt = make_optimizer("adam", 1e-2)
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                ("data", "model"))
    out = {}
    for zero1 in (False, True):
        opt_specs = (zero1_tp_opt_specs(opt, params, specs, mesh)
                     if zero1 else None)
        step = make_tp_train_step(loss, opt, mesh, params, param_specs=specs,
                                  opt_state_specs=opt_specs, donate=False)
        st = init_train_state(params, opt, jax.random.PRNGKey(1))
        st = st._replace(params=place_params(st.params, specs, mesh))
        if zero1:
            st = st._replace(
                opt_state=place_params(st.opt_state, opt_specs, mesh))
        losses = []
        for b in batches:
            st, m = step(st, b)
            losses.append(float(m["loss"]))
        out[zero1] = (losses, st)
    np.testing.assert_allclose(out[True][0], out[False][0], rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7),
        out[True][1].params, out[False][1].params,
    )
