"""Token-weighted evaluation (train/loop.py evaluate): batch losses are
per-token means, so the cross-batch aggregate must weight by token count to
be the exact corpus-level loss under unequal batch sizes."""

import jax.numpy as jnp

from lstm_tensorspark_tpu.train.loop import evaluate, make_eval_step


def test_evaluate_weights_by_tokens():
    # loss_fn reporting per-token mean loss + token count per batch
    def loss_fn(params, batch, rng):
        loss = jnp.asarray(batch["loss"], jnp.float32)
        return loss, {"tokens": jnp.asarray(batch["tokens"], jnp.float32)}

    step = make_eval_step(loss_fn, jit=False)
    # batch A: 100 tokens at loss 1.0; batch B: 10 tokens at loss 11.0
    batches = [
        {"loss": 1.0, "tokens": 100.0},
        {"loss": 11.0, "tokens": 10.0},
    ]
    out = evaluate(step, None, batches)
    # exact corpus mean = (1.0*100 + 11.0*10) / 110, NOT (1.0+11.0)/2
    expected = (1.0 * 100 + 11.0 * 10) / 110
    assert abs(out["eval_loss"] - expected) < 1e-6


def test_evaluate_unweighted_fallback():
    """Losses without a token count average uniformly (legacy behavior)."""

    def loss_fn(params, batch, rng):
        return jnp.asarray(batch, jnp.float32), {}

    step = make_eval_step(loss_fn, jit=False)
    out = evaluate(step, None, [2.0, 4.0])
    assert abs(out["eval_loss"] - 3.0) < 1e-6
