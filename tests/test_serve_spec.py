"""Lossless speculative decoding (serve/engine.py ``attach_draft`` /
``spec_window`` / ``spec_window_next`` + serve/batcher.py speculative
scheduling + serve/autotune.py's spec_k knob).

The contract under test:

- greedy speculative output is TOKEN-IDENTICAL to plain greedy decode
  (scan AND Pallas verify windows) no matter how bad the draft is — the
  target verifies every proposal in one teacher-forced pass, so draft
  quality only moves the acceptance rate, never a token;
- O(1) rollback: an ALL-REJECT speculative step (a crafted draft whose
  argmax never matches the target's) leaves engine state — the h/c slot
  rows, the session cursor, the prefix cache — bitwise-identical to
  never speculating, including across a SessionTiers spill/promote round
  trip;
- the spec compile lattice stays bounded and replay-zero, and moving
  K_draft across warmed spec-ladder rungs (``set_spec_k`` — exactly the
  autotuner's move) costs zero mid-traffic compiles;
- the autotuner's spec_k law: saturating acceptance walks K up (slow,
  patience_up), wasted verify depth walks it down fast (patience_down),
  and rung 0 = plain decode re-probes only on live decode-traffic
  evidence (at rung 0 no acceptance evidence can ever accumulate).
"""

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.serve import (
    PAD_TOKEN,
    AutoTuneConfig,
    AutoTuner,
    Batcher,
    Request,
    ServeEngine,
    ServeServer,
)
from lstm_tensorspark_tpu.train.distill import draft_config

_CFG = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)
_DCFG = draft_config(_CFG)


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 37, size=n).astype(np.int32)


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(11), _CFG)


@pytest.fixture(scope="module")
def draft_params():
    """An UNDISTILLED (random-init) draft: token parity must hold for it
    exactly as for a distilled one — only acceptance differs."""
    return init_lm(jax.random.PRNGKey(5), _DCFG)


def _wrong_draft(avoid_tokens):
    """A draft whose argmax is a CONSTANT token the target never emits:
    zero weights everywhere, one spiked head bias — so every proposal is
    rejected and every spec window emits exactly the one correction
    token (the all-reject worst case the rollback property needs)."""
    wrong = next(t for t in range(_CFG.vocab_size)
                 if t not in set(int(x) for x in avoid_tokens))
    zeros = jax.tree_util.tree_map(np.zeros_like,
                                   init_lm(jax.random.PRNGKey(0), _DCFG))
    bias = np.zeros((_CFG.vocab_size,), np.float32)
    bias[wrong] = 10.0
    zeros["head"]["bias"] = bias
    return zeros, wrong


def _engine(params, **kw):
    kw.setdefault("num_slots", 8)
    kw.setdefault("prefill_buckets", (4, 8))
    kw.setdefault("batch_buckets", (1, 2, 4))
    return ServeEngine(params, _CFG, **kw)


def _ref(params, prompt, n_new):
    gen = make_generate_fn(_CFG, max_new_tokens=n_new, greedy=True)
    return [int(t) for t in np.asarray(
        gen(params, prompt[None, :], jax.random.PRNGKey(0)))[0, prompt.size:]]


def _spec_stream(engine, slot, first_tok, n_new, k_draft):
    """Chain fresh spec windows until ``n_new`` tokens emitted; returns
    (tokens, emitted-per-window)."""
    out, per_window = [int(first_tok)], []
    while len(out) < n_new:
        win = engine.spec_window([slot], [out[-1]],
                                 [n_new - len(out)], k_draft=k_draft)
        row = ServeEngine.fetch_window(win)[0]
        emitted = [int(t) for t in row if int(t) != PAD_TOKEN]
        assert emitted, row
        per_window.append(len(emitted))
        out.extend(emitted)
    return out[:n_new], per_window


# ---- greedy token parity (the lossless claim) ----------------------------


def test_spec_engine_greedy_matches_generate(params, draft_params):
    """Engine-level chained spec windows == models/generate.py, with a
    random (undistilled) draft — parity is by construction, not by
    draft quality."""
    engine = _engine(params)
    engine.attach_draft(draft_params, _DCFG, version=1)
    p = _prompt(4, 1)
    n_new = 12
    slot, _ = engine.cache.acquire("s")
    first = engine.prefill([(slot, True, p)])
    got, _ = _spec_stream(engine, slot, first[0], n_new, k_draft=2)
    assert got == _ref(params, p, n_new)


def test_spec_window_next_pipelined_parity(params, draft_params):
    """The dispatch-ahead spec chain (spec_window_next from device
    handles, K_draft moved mid-stream like the autotuner would) stays
    token-identical to the reference."""
    engine = _engine(params)
    engine.attach_draft(draft_params, _DCFG, version=1)
    p = _prompt(5, 2)
    slot, _ = engine.cache.acquire("s")
    first = engine.prefill([(slot, True, p)])
    out = [int(first[0])]
    win = engine.spec_window([slot], [out[0]], [32], k_draft=2)
    nxt = engine.spec_window_next(win, k_draft=4)  # knob move mid-chain
    for w in (win, nxt):
        row = ServeEngine.fetch_window(w)[0]
        out.extend(int(t) for t in row if int(t) != PAD_TOKEN)
    assert out[: len(out)] == _ref(params, p, 32)[: len(out)]


def test_spec_batcher_greedy_parity_and_windows_dispatched(params,
                                                           draft_params):
    """Scheduler-level: a speculative Batcher serves token-identical
    greedy output AND actually dispatches spec windows (parity alone
    could pass with speculation inert)."""
    engine = _engine(params)
    engine.attach_draft(draft_params, _DCFG, version=1)
    batcher = Batcher(engine, max_active=4, queue_size=16,
                      speculative=True, spec_ladder=(2, 4))
    reqs = [Request(_prompt(3 + i, 7 + i), 14) for i in range(3)]
    for r in reqs:
        batcher.submit(r)
    batcher.drain()
    for i, r in enumerate(reqs):
        assert r.error is None
        assert r.tokens == _ref(params, _prompt(3 + i, 7 + i), 14)
    assert sum(batcher.spec_windows_dispatched.values()) > 0


def test_spec_pallas_window_matches_scan(params, draft_params):
    """The fused Pallas verify window (interpret mode off-TPU) is
    token-identical to the scan spec window — and actually ran (the
    compile-count key proves it was not a silent scan fallback)."""
    scan_eng = _engine(params)
    scan_eng.attach_draft(draft_params, _DCFG, version=1)
    pallas_eng = _engine(params, decode_kernel="pallas")
    pallas_eng.attach_draft(draft_params, _DCFG, version=1)
    p = _prompt(4, 3)
    n_new = 10
    streams = {}
    for name, engine in (("scan", scan_eng), ("pallas", pallas_eng)):
        slot, _ = engine.cache.acquire("s")
        first = engine.prefill([(slot, True, p)])
        streams[name], _ = _spec_stream(engine, slot, first[0], n_new,
                                        k_draft=2)
    assert streams["pallas"] == streams["scan"] == _ref(params, p, n_new)
    assert any(k[0] == "spec_window_pallas"
               for k in pallas_eng.compile_counts), (
        dict(pallas_eng.compile_counts))


# ---- O(1) rollback: the all-reject property ------------------------------


def test_all_reject_spec_state_bitwise_identical(params):
    """EVERY proposal rejected: each spec window must emit exactly one
    token (the target's correction), the stream must equal plain greedy
    decode, and the committed h/c slot state must be BITWISE identical
    to an engine that never speculated — the O(1)-rollback property
    (neither model's carry ever latched past the last emission, so
    rejection costs nothing to undo)."""
    p = _prompt(4, 9)
    n_new = 8
    ref = _ref(params, p, n_new)
    wrong_draft, wrong_tok = _wrong_draft(ref)

    spec_eng = _engine(params)
    spec_eng.attach_draft(wrong_draft, _DCFG, version=1)
    plain_eng = _engine(params)

    sslot, _ = spec_eng.cache.acquire("s")
    pslot, _ = plain_eng.cache.acquire("s")
    sfirst = spec_eng.prefill([(sslot, True, p)])
    pfirst = plain_eng.prefill([(pslot, True, p)])
    assert int(sfirst[0]) == int(pfirst[0]) == ref[0]

    spec_got, per_window = _spec_stream(spec_eng, sslot, sfirst[0], n_new,
                                        k_draft=2)
    assert spec_got == ref
    # all-reject: every window emitted ONLY its correction token
    assert per_window == [1] * (n_new - 1), per_window
    assert wrong_tok not in spec_got

    plain_got = [int(pfirst[0])]
    while len(plain_got) < n_new:
        win = plain_eng.decode_window([pslot], [plain_got[-1]],
                                      [n_new - len(plain_got)], window=1)
        row = ServeEngine.fetch_window(win)[0]
        plain_got.extend(int(t) for t in row if int(t) != PAD_TOKEN)
    assert plain_got == ref

    sh, sc = spec_eng.cache.read_slots([sslot])
    ph, pc = plain_eng.cache.read_slots([pslot])
    np.testing.assert_array_equal(np.asarray(sh), np.asarray(ph))
    np.testing.assert_array_equal(np.asarray(sc), np.asarray(pc))


def test_all_reject_rollback_bitwise_across_tiers(params):
    """The rollback property survives SessionTiers spill/promote: one
    device slot, two sessions ping-ponging through the host tier (every
    switch LRU-evicts one session into the spill worker, every return
    promotes it through the fill path). Final detached states must be
    BITWISE identical between the all-reject speculative engine and a
    never-speculating one stepping at the same grain — the all-reject
    spec window commits exactly one decode_one step, as does a window=1
    plain decode; matched per-step program granularity is what makes a
    bitwise comparison meaningful across XLA programs."""
    pa, pb = _prompt(4, 21), _prompt(5, 22)
    ref_a = _ref(params, pa, 9)
    ref_b = _ref(params, pb, 9)
    wrong_draft, _ = _wrong_draft(ref_a + ref_b)

    def run(speculative):
        engine = _engine(params, num_slots=1, tiered_cache=True,
                         host_tier_entries=4)
        if speculative:
            engine.attach_draft(wrong_draft, _DCFG, version=1)

        toks = {}
        prompts = {"A": pa, "B": pb}

        def ensure(sid):
            """Resident slot for ``sid``: fresh prefill on first touch,
            a tiers promote after (spilling whoever held the slot)."""
            slot = engine.cache.lookup(sid)
            if slot is None:
                slot, _ = engine.cache.acquire(sid)
                if sid not in toks:
                    first = engine.prefill([(slot, True, prompts[sid])])
                    toks[sid] = [int(first[0])]
                else:
                    assert engine.tiers.fill(sid, slot)
            return slot

        def advance(sid, n):
            slot = ensure(sid)
            while n > 0:
                if speculative:
                    win = engine.spec_window([slot], [toks[sid][-1]], [n],
                                             k_draft=2)
                else:
                    win = engine.decode_window([slot], [toks[sid][-1]],
                                               [n], window=1)
                emitted = [int(t) for t in ServeEngine.fetch_window(win)[0]
                           if int(t) != PAD_TOKEN]
                assert len(emitted) == 1  # all-reject: correction only
                toks[sid].extend(emitted)
                n -= len(emitted)

        advance("A", 3)
        advance("B", 3)  # evicts A through the spill worker
        for sid in ("A", "B", "A", "B"):  # promote/evict round trips
            advance(sid, 2)

        def detached(sid):
            ensure(sid)  # promote back if the last switch spilled it
            return engine.detach_session(sid)

        return toks, {sid: detached(sid) for sid in ("A", "B")}

    spec_toks, spec_states = run(speculative=True)
    plain_toks, plain_states = run(speculative=False)
    assert spec_toks == plain_toks
    assert spec_toks["A"] == ref_a[: len(spec_toks["A"])]
    assert spec_toks["B"] == ref_b[: len(spec_toks["B"])]
    for sid in ("A", "B"):
        np.testing.assert_array_equal(np.asarray(spec_states[sid].h),
                                      np.asarray(plain_states[sid].h))
        np.testing.assert_array_equal(np.asarray(spec_states[sid].c),
                                      np.asarray(plain_states[sid].c))


def test_all_reject_kept_sessions_across_tiers_token_identical(params):
    """Scheduler-level tiers leg: kept sessions whose continuations
    promote from the host tier under the REAL batcher serve the same
    tokens with an all-reject draft attached as without one — the
    session cursor survives speculation across spill/promote. (Bitwise
    state equality lives in the matched-granularity test above: the
    plain batcher schedules differently-shaped window programs whose
    fused float math can differ from the spec windows' in final ULPs,
    so cross-program state here is token-exact, not bit-exact.)"""
    pa, pb = _prompt(4, 23), _prompt(5, 24)
    ref_a = _ref(params, pa, 12)
    ref_b = _ref(params, pb, 12)
    wrong_draft, _ = _wrong_draft(ref_a + ref_b)

    def run(speculative):
        engine = _engine(params, num_slots=1, tiered_cache=True,
                         host_tier_entries=4)
        kw = {}
        if speculative:
            engine.attach_draft(wrong_draft, _DCFG, version=1)
            kw = dict(speculative=True, spec_ladder=(2, 4))
        batcher = Batcher(engine, max_active=1, queue_size=8, **kw)
        toks, sids = {}, {}
        # interleaved kept sessions: every continuation promotes its
        # session from the host tier and spills the other
        for name, prompt in (("A", pa), ("B", pb)):
            r = Request(prompt, 6, keep_session=True)
            batcher.submit(r)
            batcher.drain()
            assert r.error is None, r.error
            toks[name] = list(r.tokens)
            sids[name] = r.session_id  # server-assigned kept-session id
        for name in ("A", "B", "A", "B"):
            r = Request([toks[name][-1]], 3, session_id=sids[name],
                        keep_session=True)
            batcher.submit(r)
            batcher.drain()
            assert r.error is None, r.error
            toks[name].extend(r.tokens)
        if speculative:
            assert sum(batcher.spec_windows_dispatched.values()) > 0
            assert batcher.spec_accepted_tokens == 0  # truly all-reject
        return toks

    spec_toks = run(speculative=True)
    plain_toks = run(speculative=False)
    assert spec_toks == plain_toks
    assert spec_toks["A"] == ref_a[: len(spec_toks["A"])]
    assert spec_toks["B"] == ref_b[: len(spec_toks["B"])]


def test_all_reject_prefix_cache_identical(params):
    """The prefix cache is untouched by speculation: the same workload
    (a repeated prompt — second request resumes from the prefix hit)
    leaves identical prefix-cache statistics and identical tokens on a
    speculative all-reject stack and a plain one."""
    p = _prompt(8, 31)
    ref = _ref(params, p, 10)
    wrong_draft, _ = _wrong_draft(ref)

    def run(speculative):
        engine = _engine(params, prefix_cache=True, prefix_stride=4)
        kw = {}
        if speculative:
            engine.attach_draft(wrong_draft, _DCFG, version=1)
            kw = dict(speculative=True, spec_ladder=(2, 4))
        batcher = Batcher(engine, max_active=2, queue_size=8, **kw)
        outs = []
        for _ in range(2):
            r = Request(p, 10)
            batcher.submit(r)
            batcher.drain()
            assert r.error is None
            outs.append(list(r.tokens))
        return outs, engine.prefix.stats()

    spec_outs, spec_prefix = run(speculative=True)
    plain_outs, plain_prefix = run(speculative=False)
    assert spec_outs == plain_outs == [ref, ref]
    assert spec_prefix == plain_prefix
    assert spec_prefix["hits"] >= 1  # the second request actually resumed


# ---- bounded compile lattice + zero-compile knob moves -------------------


def test_spec_compile_lattice_bounded_and_replay_zero(params, draft_params):
    """≤1 compile per ("spec_window", batch-bucket, K_draft) — and a
    replay of the same shapes compiles nothing new."""
    engine = _engine(params)
    engine.attach_draft(draft_params, _DCFG, version=1)
    batcher = Batcher(engine, max_active=4, queue_size=16,
                      speculative=True, spec_ladder=(2, 4))

    def workload(seed):
        reqs = [Request(_prompt(3 + i, seed + i), 12) for i in range(3)]
        for r in reqs:
            batcher.submit(r)
        batcher.drain()
        assert all(r.error is None and len(r.tokens) == 12 for r in reqs)

    workload(40)
    counts = dict(engine.compile_counts)
    assert counts and all(v == 1 for v in counts.values()), counts
    skeys = [k for k in counts if k[0] == "spec_window"]
    assert skeys, counts  # the speculative path actually compiled
    for k in skeys:
        assert k[1] in engine.batch_buckets
        assert k[2] in batcher.spec_ladder and k[2] >= 1
    assert len(skeys) <= (len(engine.batch_buckets)
                          * (len(batcher.spec_ladder) - 1))  # rung 0: none
    workload(60)
    assert dict(engine.compile_counts) == counts


def test_set_spec_k_moves_cost_zero_compiles(params, draft_params):
    """Walking K_draft over the warmed ladder — including rung 0 (plain
    decode) and back up — mid-serving compiles NOTHING: exactly the
    autotuner's guarantee that a knob move never charges a request an
    XLA compile."""
    engine = _engine(params)
    engine.attach_draft(draft_params, _DCFG, version=1)
    server = ServeServer(engine, max_active=4, queue_size=16,
                         speculative=True, spec_ladder=(2, 4))
    with server:
        server.warmup(prompt_lens=(4, 8))
        n0 = engine.num_compiles()
        for k in (0, 2, 4, 2, 0, 4):
            server.batcher.set_spec_k(k)
            req = server.generate(_prompt(4, 50), max_new_tokens=9)
            assert req.error is None, req.error
            assert list(req.tokens) == _ref(params, _prompt(4, 50), 9)
        assert engine.num_compiles() == n0


def test_set_spec_k_validates_ladder_and_mode(params, draft_params):
    engine = _engine(params)
    engine.attach_draft(draft_params, _DCFG, version=1)
    b = Batcher(engine, max_active=2, queue_size=4,
                speculative=True, spec_ladder=(2, 4))
    assert b.spec_ladder == (0, 2, 4)  # rung 0 always present
    assert b.spec_k == 4  # boot default: the top rung
    with pytest.raises(ValueError):
        b.set_spec_k(3)  # not a warmed rung
    plain = Batcher(_engine(params), max_active=2, queue_size=4)
    with pytest.raises(ValueError):
        plain.set_spec_k(2)  # not a speculative scheduler
    with pytest.raises(ValueError):
        # speculative boot without a draft attached
        Batcher(_engine(params), max_active=2, queue_size=4,
                speculative=True)


# ---- the autotuner's spec_k law ------------------------------------------


def _sig(*, itl=(0, None), qwait=(0, None), ttft=(0, None), queued=0,
         queue_size=8, chunks=0.0, tiers=None, spec_accept=None):
    def h(pair):
        count, p99 = pair
        out = {"count": count, "sum": 0.0}
        if p99 is not None:
            out["p50"] = p99 / 2
            out["p99"] = p99
        return out

    return {"ttft": h(ttft), "itl": h(itl), "queue_wait": h(qwait),
            "queued": queued, "queue_size": queue_size,
            "prefill_chunks": chunks, "tiers": tiers,
            "spec_accept": spec_accept}


def _accept(count, mean):
    return {"count": count, "sum": count * mean}


def _spec_server(params, draft_params):
    engine = _engine(params)
    engine.attach_draft(draft_params, _DCFG, version=1)
    return ServeServer(engine, max_active=4, queue_size=8,
                       window_ladder=(1, 2, 4),
                       speculative=True, spec_ladder=(2, 4))


def _tuner(server, **cfg_kw):
    cfg_kw.setdefault("slo_s", 0.2)
    cfg_kw.setdefault("min_events", 4)
    cfg_kw.setdefault("patience_up", 2)
    cfg_kw.setdefault("patience_down", 1)
    cfg_kw.setdefault("cooldown", 0)
    return AutoTuner(server, AutoTuneConfig(**cfg_kw))


def _spec_moves(moves):
    return [(m["knob"], m["direction"]) for m in moves
            if m["knob"] == "spec_k"]


def test_tuner_spec_k_up_on_saturating_acceptance(params, draft_params):
    server = _spec_server(params, draft_params)
    server.batcher.set_spec_k(2)  # mid-ladder operating point
    tuner = _tuner(server)
    sat = _sig(spec_accept=_accept(8, 1.8))  # mean 1.8 >= 0.8 * 2
    assert _spec_moves(tuner.tick(sat)) == []  # patience_up = 2
    assert _spec_moves(tuner.tick(sat)) == [("spec_k", "up")]
    assert server.batcher.spec_k == 4
    for _ in range(4):  # at the top rung: no overshoot
        tuner.tick(_sig(spec_accept=_accept(8, 3.6)))
    assert server.batcher.spec_k == 4


def test_tuner_spec_k_down_fast_and_rung0_is_plain_decode(params,
                                                          draft_params):
    server = _spec_server(params, draft_params)
    tuner = _tuner(server)
    assert server.batcher.spec_k == 4
    waste = _sig(spec_accept=_accept(8, 0.4))  # mean < 0.5 * K: fast down
    assert _spec_moves(tuner.tick(waste)) == [("spec_k", "down")]
    assert server.batcher.spec_k == 2
    assert _spec_moves(tuner.tick(waste)) == [("spec_k", "down")]
    assert server.batcher.spec_k == 0  # the K=0 fallback: plain decode
    # at rung 0 there is NO acceptance evidence — stale acceptance
    # deltas must not move the knob; only live decode traffic re-probes
    assert _spec_moves(tuner.tick(waste)) == []
    assert server.batcher.spec_k == 0


def test_tuner_spec_k_rung0_reprobes_on_decode_traffic(params,
                                                       draft_params):
    server = _spec_server(params, draft_params)
    server.batcher.set_spec_k(0)
    tuner = _tuner(server)
    quiet = _sig()  # no traffic: stay parked at plain decode
    for _ in range(3):
        assert _spec_moves(tuner.tick(quiet)) == []
    assert server.batcher.spec_k == 0
    busy = _sig(itl=(20, 0.002))  # live decode traffic: re-probe
    assert _spec_moves(tuner.tick(busy)) == []  # patience_up = 2
    assert _spec_moves(tuner.tick(busy)) == [("spec_k", "up")]
    assert server.batcher.spec_k == 2


def test_tuner_spec_k_inert_on_nonspeculative_stack(params):
    server = ServeServer(_engine(params), max_active=4, queue_size=8)
    tuner = _tuner(server)
    for _ in range(3):
        assert _spec_moves(tuner.tick(
            _sig(itl=(20, 0.002), spec_accept=_accept(8, 3.0)))) == []
    assert tuner.stats()["knobs"]["spec_k"] == {"value": None, "ladder": []}
