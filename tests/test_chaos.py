"""Chaos tests (tier-1, CPU): the crash→restart→resume cycle driven by the
fault plane, end to end. The headline test runs the REAL supervisor over
REAL CLI subprocesses with a crash AND a corrupted checkpoint injected, and
asserts the run still completes its exact step budget."""

import json
import os
import subprocess
import sys

import pytest

from lstm_tensorspark_tpu.resilience import faults
from lstm_tensorspark_tpu.resilience.exit_codes import FAULT_CRASH_RC

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.disarm()
    yield
    # explicit pop, not monkeypatch: the CLI EXPORTS the var mid-test
    # (--faults -> env for children) and delenv-on-absent records no undo
    os.environ.pop(faults.ENV_VAR, None)
    faults.disarm()


def _cli_flags(steps, ckpt, jsonl):
    return [
        "--dataset", "ptb_char", "--hidden-units", "8", "--batch-size", "8",
        "--seq-len", "16", "--backend", "single", "--num-steps", str(steps),
        "--log-every", "1", "--checkpoint-dir", str(ckpt),
        "--checkpoint-every", "2", "--jsonl", str(jsonl),
    ]


def _records(jsonl):
    return [json.loads(line) for line in open(jsonl)]


def test_supervised_crash_and_corrupt_ckpt_complete_budget(tmp_path):
    """Real subprocesses: child 1 corrupts its step-4 checkpoint (after the
    write), then hard-crashes before step 5 (rc FAULT_CRASH_RC). The
    supervisor relaunches with --resume; child 2's restore quarantines the
    corrupt step 4, falls back to step 2, and finishes the exact budget."""
    ckpt, jsonl = tmp_path / "ckpt", tmp_path / "m.jsonl"
    cmd = [
        sys.executable, "-m", "lstm_tensorspark_tpu.supervise",
        "--max-restarts", "2", "--restart-delay", "0.1", "--max-delay", "1",
        "--",
        *_cli_flags(6, ckpt, jsonl),
        "--faults", "crash@5;ckpt_corrupt@4",
    ]
    out = subprocess.run(cmd, cwd=_REPO, capture_output=True, text=True,
                         timeout=420)
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-3000:])
    records = _records(jsonl)
    finals = [r for r in records if r.get("note") == "final"]
    assert finals[-1]["step"] == 6  # exact budget despite crash + corruption
    assert any("resumed at step 2" in str(r.get("note", "")) for r in records)
    # forensics: the corrupt newest was quarantined, not deleted
    assert any(n.endswith(".quarantined") for n in os.listdir(ckpt))
    # both one-shot faults actually fired (marker files under .faults/)
    fired = os.listdir(ckpt / ".faults")
    assert "crash@5.fired" in fired and "ckpt_corrupt@4.fired" in fired
    # the crash child exited with the dedicated injected-crash rc
    assert f"child exited {FAULT_CRASH_RC}" in out.stderr


def test_data_error_retried_and_matches_uninterrupted_loss(tmp_path):
    """data_error fault: the batch feed raises InjectedFault mid-run, the
    supervisor retries, the resumed run completes the budget — and its
    final eval equals an uninjected run's bit-for-bit (data-exact resume:
    a crash changes WHEN steps ran, never WHAT they computed; NaN faults
    are excluded here because skipping updates legitimately alters the
    trajectory). Runs the CLI in-process via an injected runner (fast
    path; crash faults need the subprocess test above — they hard-exit)."""
    from lstm_tensorspark_tpu.cli import main as cli_main
    from lstm_tensorspark_tpu.supervise import supervise

    clean_jsonl = tmp_path / "clean.jsonl"
    assert cli_main(_cli_flags(6, tmp_path / "ckpt_clean", clean_jsonl)) == 0
    clean = [r for r in _records(clean_jsonl) if r.get("note") == "final"][-1]

    ckpt, jsonl = tmp_path / "ckpt", tmp_path / "m.jsonl"
    attempts = []

    def runner(argv):
        attempts.append(list(argv))
        try:
            return cli_main(argv)
        except faults.InjectedFault:
            return 1  # a real child would die with a traceback, rc 1

    base = [*_cli_flags(6, ckpt, jsonl), "--faults", "data_error@4"]
    rc = supervise(base, max_restarts=2, restart_delay=0.0, runner=runner)
    assert rc == 0
    assert len(attempts) == 2 and "--resume" in attempts[1]
    assert os.path.exists(ckpt / ".faults" / "data_error@4.fired")
    chaos = [r for r in _records(jsonl) if r.get("note") == "final"][-1]
    assert chaos["step"] == clean["step"] == 6
    assert chaos["eval_loss"] == pytest.approx(clean["eval_loss"], abs=1e-6)
