"""utils/tracing: span capture, Chrome trace JSON output, CLI --trace, and
no-op behavior when disabled."""

import json
import time

from lstm_tensorspark_tpu.utils import Tracer, get_tracer, instant, set_tracer, span


def test_tracer_records_spans_and_saves(tmp_path):
    t = Tracer()
    with t.span("outer", phase="x"):
        time.sleep(0.01)
        with t.span("inner"):
            pass
    t.instant("marker", step=3)
    path = tmp_path / "trace.json"
    t.save(str(path))
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    data = [e for e in events if e["ph"] != "M"]
    names = [e["name"] for e in data]
    assert set(names) == {"outer", "inner", "marker"}
    outer = next(e for e in events if e["name"] == "outer")
    inner = next(e for e in events if e["name"] == "inner")
    assert outer["ph"] == "X" and outer["dur"] >= 10_000  # >= 10ms in us
    assert outer["args"] == {"phase": "x"}
    # inner nested within outer's interval
    assert outer["ts"] <= inner["ts"] <= inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    # recording threads are named via thread_name METADATA events (full
    # tid, no 16-bit truncation that could fold two threads onto one row)
    import threading

    metas = [e for e in events if e["ph"] == "M"]
    assert any(e["name"] == "thread_name"
               and e["tid"] == threading.get_ident()
               and e["args"]["name"] == threading.current_thread().name
               for e in metas)
    assert outer["tid"] == threading.get_ident()


def test_tracer_ring_buffer_caps_events(tmp_path):
    """Long serving runs must not grow the event list without bound: the
    ring keeps the NEWEST max_events and counts what it displaced."""
    t = Tracer(max_events=10)
    for i in range(25):
        t.instant(f"e{i}")
    assert t.dropped == 15
    path = tmp_path / "ring.json"
    t.save(str(path))
    events = json.loads(path.read_text())["traceEvents"]
    kept = [e["name"] for e in events if e["name"].startswith("e")]
    assert kept == [f"e{i}" for i in range(15, 25)]  # newest survive
    drop = next(e for e in events if e["name"] == "tracer_dropped_events")
    assert drop["args"]["dropped"] == 15


def test_tracer_complete_and_tid_names(tmp_path):
    """complete(): spans from explicit perf_counter stamps on a synthetic
    named row — how serve emits per-request timelines after the fact."""
    t = Tracer()
    a = time.perf_counter()
    time.sleep(0.005)
    b = time.perf_counter()
    t.complete("queue", a, b, tid=42, request=7)
    t.set_tid_name(42, "request 7")
    path = tmp_path / "c.json"
    t.save(str(path))
    events = json.loads(path.read_text())["traceEvents"]
    ev = next(e for e in events if e["name"] == "queue")
    assert ev["tid"] == 42 and ev["ph"] == "X"
    assert 4_000 <= ev["dur"] <= 500_000  # ~5ms in us (scheduler slack)
    assert ev["args"]["request"] == 7
    assert any(e["ph"] == "M" and e["tid"] == 42
               and e["args"]["name"] == "request 7" for e in events)


def test_module_helpers_noop_when_disabled():
    set_tracer(None)
    assert get_tracer() is None
    with span("nothing") as t:
        assert t is None
    instant("nothing")  # must not raise


def test_module_helpers_record_when_installed(tmp_path):
    t = Tracer()
    set_tracer(t)
    try:
        with span("phase"):
            instant("tick")
    finally:
        set_tracer(None)
    path = tmp_path / "t.json"
    t.save(str(path))
    names = [e["name"] for e in json.loads(path.read_text())["traceEvents"]]
    assert names.count("phase") == 1 and names.count("tick") == 1


def test_cli_trace_end_to_end(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    trace = tmp_path / "host_trace.json"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "32", "--batch-size", "8",
        "--num-steps", "2", "--log-every", "1", "--backend", "single",
        "--trace", str(trace),
    ])
    assert rc == 0
    names = {e["name"] for e in json.loads(trace.read_text())["traceEvents"]}
    assert {"load_dataset", "setup", "train", "eval_final"} <= names
    assert get_tracer() is None  # uninstalled after the run


def test_log_flops_records(tmp_path):
    """--log-flops: throughput records carry model_tflops + mfu, computed
    from the shared utils/flops formulas."""
    import json

    from lstm_tensorspark_tpu.cli import main
    from lstm_tensorspark_tpu.utils.flops import (
        PEAK_TFLOPS, TRAIN_FLOPS_MULTIPLIER, lm_fwd_flops_per_token,
    )

    jsonl = tmp_path / "m.jsonl"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--num-steps", "4",
        "--log-every", "2", "--log-flops", "--backend", "single",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    recs = [json.loads(l) for l in open(jsonl)]
    th = [r for r in recs if "tokens_per_sec" in r]
    assert th and all("model_tflops" in r and "mfu" in r for r in th)
    r = th[-1]
    # vocab size from the run's own start record (synthetic stand-in or a
    # real corpus — the test must match whatever the CLI loaded)
    V = next(rec["vocab"] for rec in recs if "vocab" in rec)
    fpt = TRAIN_FLOPS_MULTIPLIER * lm_fwd_flops_per_token(V, 16, 1)
    import numpy as np
    np.testing.assert_allclose(
        r["model_tflops"], r["tokens_per_sec"] * fpt / 1e12, rtol=1e-6
    )
    # single-chip run (--backend single): aggregate peak = one chip's
    np.testing.assert_allclose(
        r["mfu"], r["model_tflops"] / PEAK_TFLOPS, atol=1e-4
    )
