"""utils/tracing: span capture, Chrome trace JSON output, CLI --trace, and
no-op behavior when disabled."""

import json
import time

from lstm_tensorspark_tpu.utils import Tracer, get_tracer, instant, set_tracer, span


def test_tracer_records_spans_and_saves(tmp_path):
    t = Tracer()
    with t.span("outer", phase="x"):
        time.sleep(0.01)
        with t.span("inner"):
            pass
    t.instant("marker", step=3)
    path = tmp_path / "trace.json"
    t.save(str(path))
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    names = [e["name"] for e in events]
    assert set(names) == {"outer", "inner", "marker"}
    outer = next(e for e in events if e["name"] == "outer")
    inner = next(e for e in events if e["name"] == "inner")
    assert outer["ph"] == "X" and outer["dur"] >= 10_000  # >= 10ms in us
    assert outer["args"] == {"phase": "x"}
    # inner nested within outer's interval
    assert outer["ts"] <= inner["ts"] <= inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]


def test_module_helpers_noop_when_disabled():
    set_tracer(None)
    assert get_tracer() is None
    with span("nothing") as t:
        assert t is None
    instant("nothing")  # must not raise


def test_module_helpers_record_when_installed(tmp_path):
    t = Tracer()
    set_tracer(t)
    try:
        with span("phase"):
            instant("tick")
    finally:
        set_tracer(None)
    path = tmp_path / "t.json"
    t.save(str(path))
    names = [e["name"] for e in json.loads(path.read_text())["traceEvents"]]
    assert names.count("phase") == 1 and names.count("tick") == 1


def test_cli_trace_end_to_end(tmp_path):
    from lstm_tensorspark_tpu.cli import main

    trace = tmp_path / "host_trace.json"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "32", "--batch-size", "8",
        "--num-steps", "2", "--log-every", "1", "--backend", "single",
        "--trace", str(trace),
    ])
    assert rc == 0
    names = {e["name"] for e in json.loads(trace.read_text())["traceEvents"]}
    assert {"load_dataset", "setup", "train", "eval_final"} <= names
    assert get_tracer() is None  # uninstalled after the run
