"""Non-finite step guard (train/loop.py): NaN/Inf losses or gradients skip
the update (params, moments, carries untouched), are counted in
``metrics["anomalous"]``, and — with ``anomaly_limit`` — abort with the
dedicated error after K consecutive bad steps. The NaN bursts come from the
fault plane, so this also covers ``nan_grads`` injection end to end."""

import os
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from lstm_tensorspark_tpu.resilience import faults
from lstm_tensorspark_tpu.train.loop import (
    AnomalousTrainingError,
    init_train_state,
    make_train_step,
    train_loop,
)


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.disarm()
    yield
    # explicit pop, not monkeypatch: the CLI EXPORTS the var mid-test
    # (--faults -> env for children) and delenv-on-absent records no undo
    os.environ.pop(faults.ENV_VAR, None)
    faults.disarm()


def _loss_fn(params, batch, rng):
    pred = params["w"] * batch["x"]
    return jnp.mean((pred - batch["y"]) ** 2), {}


def _batch(x, y):
    return {"x": jnp.asarray(x, jnp.float32), "y": jnp.asarray(y, jnp.float32)}


def _state(w=2.0):
    opt = optax.sgd(0.1)
    return (init_train_state({"w": jnp.asarray(w)}, opt,
                             jax.random.PRNGKey(0)), opt)


def test_nan_batch_skips_update_and_counts():
    state, opt = _state()
    step = make_train_step(_loss_fn, opt, jit=True)
    bad = _batch([jnp.nan, 1.0], [0.0, 0.0])
    good = _batch([1.0, 2.0], [0.0, 0.0])

    s1, m1 = step(state, bad)
    assert float(m1["anomalous"]) == 1.0
    assert not np.isfinite(float(m1["loss"]))
    # update skipped: params and moments bit-identical, step/rng advanced
    assert float(s1.params["w"]) == float(state.params["w"])
    assert int(s1.step) == 1

    s2, m2 = step(s1, good)
    assert float(m2["anomalous"]) == 0.0
    assert float(s2.params["w"]) != float(s1.params["w"])  # healthy again
    assert np.isfinite(float(s2.params["w"]))


def test_injected_nan_burst_matches_skip_replay():
    """nan_grads@2x2 poisons steps 2-3; the final params must equal a clean
    run that simply never took those two steps (proof the burst cannot
    leak into params or moments)."""
    good = [_batch([1.0, 2.0], [0.5, 0.1]), _batch([3.0, 1.0], [0.2, 0.9]),
            _batch([2.0, 2.0], [0.1, 0.3]), _batch([1.5, 0.5], [0.4, 0.2])]

    faults.arm("nan_grads@2x2")
    state, opt = _state()
    step = make_train_step(_loss_fn, opt, jit=True)
    flags = []
    for b in good:
        state, m = step(state, b)
        flags.append(float(m["anomalous"]))
    assert flags == [0.0, 1.0, 1.0, 0.0]
    faulted_w = float(state.params["w"])

    faults.disarm()
    ref, opt2 = _state()
    ref_step = make_train_step(_loss_fn, opt2, jit=True)
    ref, _ = ref_step(ref, good[0])
    # steps 2-3 skipped everything except step/rng advance
    ref = ref._replace(step=ref.step + 2,
                       rng=jax.random.split(jax.random.split(ref.rng)[0])[0])
    ref, _ = ref_step(ref, good[3])
    assert faulted_w == pytest.approx(float(ref.params["w"]), abs=1e-6)
    assert int(state.step) == 4


def test_multistep_counts_anomalous_in_window():
    from lstm_tensorspark_tpu.train.multistep import make_multi_train_step

    faults.arm("nan_grads@2x2")
    state, opt = _state()
    mstep = make_multi_train_step(_loss_fn, opt, jit=True)
    stacked = {"x": jnp.ones((4, 2), jnp.float32),
               "y": jnp.zeros((4, 2), jnp.float32)}
    state, ms = mstep(state, stacked)
    assert float(ms["anomalous"]) == 2.0
    assert np.isfinite(float(state.params["w"]))


def test_train_loop_aborts_after_k_consecutive():
    faults.arm("nan_grads@1x50")
    state, opt = _state()
    step = make_train_step(_loss_fn, opt, jit=True)
    batches = iter([_batch([1.0, 1.0], [0.0, 0.0])] * 50)
    with pytest.raises(AnomalousTrainingError) as ei:
        train_loop(state, step, batches, num_steps=50, log_every=0,
                   anomaly_limit=3)
    assert ei.value.consecutive == 3
    assert ei.value.total == 3


def test_train_loop_burst_below_limit_completes():
    faults.arm("nan_grads@2x2")
    state, opt = _state()
    step = make_train_step(_loss_fn, opt, jit=True)
    batches = iter([_batch([1.0, 1.0], [0.0, 0.0])] * 8)
    out = train_loop(state, step, batches, num_steps=8, log_every=0,
                     anomaly_limit=3)
    assert int(out.step) == 8
    assert np.isfinite(float(out.params["w"]))


def test_cli_anomaly_abort_exit_code(tmp_path, monkeypatch):
    """Full CLI path: a persistent NaN burst with --anomaly-limit returns
    the dedicated rc, and the checkpoints on disk stay clean."""
    from lstm_tensorspark_tpu.cli import main as cli_main
    from lstm_tensorspark_tpu.resilience.exit_codes import ANOMALY_RC

    rc = cli_main([
        "--dataset", "ptb_char", "--hidden-units", "8", "--batch-size", "8",
        "--seq-len", "16", "--backend", "single", "--num-steps", "10",
        "--log-every", "1", "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--checkpoint-every", "2", "--jsonl", str(tmp_path / "m.jsonl"),
        "--faults", "nan_grads@3x50", "--anomaly-limit", "4",
    ])
    assert rc == ANOMALY_RC
