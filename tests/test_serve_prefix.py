"""Prefix-state cache + chunked prefill (serve/state_cache.PrefixCache,
serve/engine prefill src/dst + prefill_chunk, serve/batcher scheduling).

The ISSUE-4 acceptance surface:

- PARITY: greedy generation is token-identical across {prefix cache on
  cold, on hot, off} and chunked prefill, all matching models/generate.py;
- cache interaction: evicting a state-cache slot that backs a prefix
  entry INVALIDATES the entry (lookups miss — never read a slot someone
  else owns); detach/restore of a session never aliases a refcounted
  prefix slot;
- chunked prefill: a long prompt's prefill is consumed <= chunk tokens
  per scheduler iteration with decode interleaved between chunks, and
  lifts the prompt-length admission cap;
- observability: /stats carries prefix-cache + compile + swap-generation
  counters.

Parity stacks build their own engines (prefix on/off is a constructor
choice); the configs are tiny so each XLA compile is subsecond on CPU.
"""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.serve import (
    Batcher,
    PrefixCache,
    Request,
    ServeEngine,
    ServeServer,
    StateCache,
)

_CFG = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)


def _make_engine(**kw):
    params = init_lm(jax.random.PRNGKey(0), _CFG)
    kw.setdefault("num_slots", 16)
    kw.setdefault("prefill_buckets", (4, 8, 16))
    kw.setdefault("batch_buckets", (1, 2, 4))
    return params, ServeEngine(params, _CFG, **kw)


def _refs(params, prompts, n_new):
    gen = make_generate_fn(_CFG, max_new_tokens=n_new, greedy=True)
    return [
        np.asarray(gen(params, p[None, :], jax.random.PRNGKey(0)))[
            0, p.size:].tolist()
        for p in prompts
    ]


def _run(batcher, prompts, n_new):
    reqs = [Request(p, n_new) for p in prompts]
    for r in reqs:
        batcher.submit(r)
    batcher.drain()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [r.tokens for r in reqs]


# ---- PrefixCache unit behaviour -----------------------------------------


def test_longest_match_and_full_prompt_cap():
    cache = StateCache(num_layers=1, num_slots=6, hidden_size=4)
    prefix = PrefixCache(cache, stride=2, max_entries=4)
    slot, _ = cache.acquire("seed")
    assert prefix.insert(np.array([1, 2], np.int32), slot)
    assert prefix.insert(np.array([1, 2, 3, 4], np.int32), slot)

    entry, n = prefix.lookup(np.array([1, 2, 3, 4, 9], np.int32))
    assert entry is not None and n == 4  # longest wins
    prefix.release(entry)
    # a matched length never covers the FULL prompt: >= 1 token must
    # remain to prefill (that token produces the first sampled logits)
    entry, n = prefix.lookup(np.array([1, 2, 3, 4], np.int32))
    assert entry is not None and n == 2
    prefix.release(entry)
    entry, n = prefix.lookup(np.array([5, 6, 7], np.int32))
    assert entry is None and n == 0
    assert prefix.stats()["misses"] == 1


def test_lookup_refcount_pins_backing_slot():
    cache = StateCache(num_layers=1, num_slots=2, hidden_size=4)
    prefix = PrefixCache(cache, stride=2, max_entries=2)
    slot, _ = cache.acquire("seed")
    assert prefix.insert(np.array([1, 2], np.int32), slot)
    cache.release("seed")

    entry, n = prefix.lookup(np.array([1, 2, 9], np.int32))
    assert n == 2 and entry.refs == 1
    # the backing slot is pinned while ref-held: churning sessions through
    # the 1 remaining free slot cannot evict it
    cache.acquire("a")
    cache.release("a")
    cache.acquire("b")
    cache.release("b")
    assert prefix.stats()["invalidated"] == 0
    prefix.release(entry)
    assert entry.refs == 0


def test_prefix_lru_eviction_releases_backing_slot():
    cache = StateCache(num_layers=1, num_slots=8, hidden_size=4)
    prefix = PrefixCache(cache, stride=2, max_entries=2)
    slot, _ = cache.acquire("seed")
    assert prefix.insert(np.array([1, 2], np.int32), slot)
    assert prefix.insert(np.array([3, 4], np.int32), slot)
    live_before = len(cache)
    assert prefix.insert(np.array([5, 6], np.int32), slot)  # evicts [1, 2]
    assert len(prefix) == 2
    assert len(cache) == live_before  # slot count unchanged: evict+insert
    assert prefix.stats()["evictions"] == 1
    entry, n = prefix.lookup(np.array([1, 2, 9], np.int32))
    assert entry is None


def test_state_cache_eviction_invalidates_dependent_entry():
    """The satellite case: LRU-evicting the state-cache slot that BACKS a
    prefix entry must invalidate the entry (miss), not corrupt it (a
    lookup reading a slot some session now owns)."""
    cache = StateCache(num_layers=1, num_slots=2, hidden_size=4)
    prefix = PrefixCache(cache, stride=2, max_entries=4)
    slot, _ = cache.acquire("seed")
    assert prefix.insert(np.array([1, 2], np.int32), slot)
    cache.release("seed")
    # entry unpinned (no refs): filling the cache with pinned sessions
    # forces the LRU to take the prefix's backing slot
    cache.acquire("a")
    cache.pin("a")
    cache.acquire("b")
    cache.pin("b")
    assert prefix.stats()["invalidated"] == 1
    entry, n = prefix.lookup(np.array([1, 2, 9], np.int32))
    assert entry is None and n == 0


def test_hit_refreshes_backing_slot_recency():
    """A prefix hit must refresh the backing slot's STATE-cache recency,
    not just the prefix LRU — otherwise slot pressure evicts the hottest
    prefix's slot first (it never reorders via pin/unpin) and the cache
    thrashes exactly under the load it exists for."""
    cache = StateCache(num_layers=1, num_slots=3, hidden_size=4)
    prefix = PrefixCache(cache, stride=2, max_entries=4)
    slot, _ = cache.acquire("seed")
    assert prefix.insert(np.array([1, 2], np.int32), slot)
    cache.release("seed")
    # age the prefix sid, then HIT it — the hit makes it most-recent
    cache.acquire("a")
    entry, _ = prefix.lookup(np.array([1, 2, 9], np.int32))
    prefix.release(entry)  # refs back to 0: unpinned, LRU-evictable
    # slot pressure: the eviction victim must be the stale "a", not the
    # just-hit prefix slot
    cache.acquire("b")
    cache.acquire("c")
    assert prefix.stats()["invalidated"] == 0
    entry, n = prefix.lookup(np.array([1, 2, 9], np.int32))
    assert entry is not None and n == 2


def test_reserved_session_namespace_rejected():
    with pytest.raises(ValueError, match="reserved"):
        Request(np.array([1, 2], np.int32), 2, session_id="prefix/7")


# ---- parity: the acceptance criterion -----------------------------------


def test_parity_cache_on_cold_hot_off_and_chunked():
    """Greedy output must be token-identical across {prefix cache on cold,
    on hot, off} x {chunked, monolithic} prefill, and match
    models/generate.py. Prompts share an 8-token prefix (stride-aligned),
    so the hot runs genuinely resume from cache entries."""
    rng = np.random.RandomState(3)
    shared = rng.randint(0, 37, size=8).astype(np.int32)
    prompts = [
        np.concatenate([shared, rng.randint(0, 37, size=5).astype(np.int32)])
        for _ in range(3)
    ]
    n_new = 6
    refs = None
    for kw_e, kw_b in [
        ({}, {}),
        ({"prefix_cache": True}, {}),
        ({"prefix_cache": True}, {"prefill_chunk": 4}),
        ({}, {"prefill_chunk": 4}),
    ]:
        params, engine = _make_engine(**kw_e)
        if refs is None:
            refs = _refs(params, prompts, n_new)
        batcher = Batcher(engine, max_active=4, queue_size=8, **kw_b)
        assert _run(batcher, prompts, n_new) == refs  # cold
        assert _run(batcher, prompts, n_new) == refs  # hot (or re-run)
        if engine.prefix is not None:
            st = engine.prefix.stats()
            assert st["hits"] >= 3, st   # the hot pass actually resumed
            assert st["inserts"] >= 1, st
            assert batcher.prefix_tokens_saved >= 8 * 3


def test_eviction_under_pressure_stays_correct():
    """Slot pressure evicting prefix entries mid-traffic must degrade to
    misses, never to wrong tokens: a cache with barely more slots than
    active sessions keeps evicting/invalidating entries while requests
    flow."""
    rng = np.random.RandomState(5)
    shared = rng.randint(0, 37, size=8).astype(np.int32)
    prompts = [
        np.concatenate([shared, rng.randint(0, 37, size=3).astype(np.int32)])
        for _ in range(4)
    ]
    n_new = 4
    params, engine = _make_engine(num_slots=5, prefix_cache=True,
                                  prefix_entries=8)
    refs = _refs(params, prompts, n_new)
    batcher = Batcher(engine, max_active=4, queue_size=8)
    for _ in range(3):
        assert _run(batcher, prompts, n_new) == refs


# ---- detach/restore vs refcounted prefix slots --------------------------


def test_detach_restore_never_aliases_prefix_slot():
    """A session detached and restored around prefix-cache traffic must
    get its own slot — never the backing slot of a live entry — and the
    entry's stored state must survive the churn bit-identically."""
    rng = np.random.RandomState(7)
    shared = rng.randint(0, 37, size=8).astype(np.int32)
    prompt = np.concatenate([shared,
                             rng.randint(0, 37, size=4).astype(np.int32)])
    n_total = 8
    params, engine = _make_engine(prefix_cache=True)
    batcher = Batcher(engine, max_active=4, queue_size=8)
    (ref,) = _refs(params, [prompt], n_total)

    k = 4
    first = Request(prompt, k, keep_session=True)
    batcher.submit(first)
    batcher.drain()
    assert first.error is None
    # the cold pass inserted the shared prefix; snapshot its device state
    entry, n = engine.prefix.lookup(np.concatenate([shared, [1]]).astype(np.int32))
    assert entry is not None and n == 8
    snap_h = np.asarray(engine.cache.h[:, entry.slot, :]).copy()
    snap_c = np.asarray(engine.cache.c[:, entry.slot, :]).copy()

    sid = first.session_id
    detached = engine.detach_session(sid)
    # churn while detached: hot traffic resumes FROM the entry (ref-held
    # above, so it cannot be evicted under us)
    churn = Request(prompt, 2)
    batcher.submit(churn)
    batcher.drain()
    assert churn.error is None

    new_slot = engine.restore_session(sid, detached)
    assert new_slot != entry.slot  # restore must not alias the entry
    second = Request(np.array([first.tokens[-1]], np.int32), n_total - k,
                     session_id=sid)
    batcher.submit(second)
    batcher.drain()
    assert second.error is None
    engine.cache.release(sid)
    assert first.tokens + second.tokens == ref

    # the entry's device state never moved under all that traffic
    np.testing.assert_array_equal(
        np.asarray(engine.cache.h[:, entry.slot, :]), snap_h)
    np.testing.assert_array_equal(
        np.asarray(engine.cache.c[:, entry.slot, :]), snap_c)
    engine.prefix.release(entry)


# ---- chunked prefill scheduling -----------------------------------------


def test_chunked_prefill_interleaves_decode():
    """While a long prompt prefills chunk-by-chunk, an already-decoding
    session must receive tokens BETWEEN chunks — the bounded-stall
    property chunking exists for."""
    params, engine = _make_engine()
    batcher = Batcher(engine, max_active=4, queue_size=8,
                      window_ladder=(1,), prefill_chunk=4)
    short = Request(np.array([5, 3], np.int32), 12)
    batcher.submit(short)
    batcher.step()  # short is admitted and decoding
    tokens_before = len(short.tokens)
    assert tokens_before >= 1

    long_prompt = np.arange(1, 17, dtype=np.int32) % 37  # 16 tokens, 4 chunks
    long_req = Request(long_prompt, 2)
    batcher.submit(long_req)
    progress = []
    while long_req.t_first_token is None:
        batcher.step()
        progress.append(len(short.tokens))
    # 16 tokens at chunk 4 = 3 intermediate chunk programs + 1 final
    assert batcher.prefill_chunks_dispatched == 3
    # the short session advanced during the long prefill, iteration by
    # iteration — not all-at-once after it
    assert progress[0] > tokens_before
    assert progress[-1] > progress[0]
    batcher.drain()
    assert short.error is None and long_req.error is None
    (ref_long,) = _refs(params, [long_prompt], 2)
    assert long_req.tokens == ref_long
    (ref_short,) = _refs(params, [np.array([5, 3], np.int32)], 12)
    assert short.tokens == ref_short


def test_chunked_prefill_lifts_prompt_length_cap():
    """Chunked prefill serves prompts LONGER than the largest prefill
    bucket (each program consumes <= chunk tokens); without it the same
    prompt is rejected at submit."""
    params, engine = _make_engine()  # largest bucket: 16
    long_prompt = (np.arange(24, dtype=np.int32) * 5 + 1) % 37
    plain = Batcher(engine, max_active=4, queue_size=8)
    with pytest.raises(ValueError, match="exceeds"):
        plain.submit(Request(long_prompt, 2))

    chunked = Batcher(engine, max_active=4, queue_size=8, prefill_chunk=8)
    (ref,) = _refs(params, [long_prompt], 4)
    assert _run(chunked, [long_prompt], 4) == [ref]


def test_warmup_precompiles_chunk_programs():
    _, engine = _make_engine(prefill_buckets=(4,), batch_buckets=(1, 2))
    engine.warmup(prompt_lens=(4,), chunk_lens=(4,))
    counts = dict(engine.compile_counts)
    assert ("prefill_chunk", 1, 4) in counts
    assert ("prefill_chunk", 2, 4) in counts
    # replaying the warmed shapes recompiles nothing
    scratch = engine.cache.scratch_slot
    engine.prefill_chunk([(scratch, scratch, True, np.zeros(3, np.int32))])
    assert dict(engine.compile_counts) == counts


@pytest.mark.parametrize("chunk", [None, 4])
def test_batcher_warmup_covers_split_programs(chunk):
    """Batcher.warmup must pre-compile the chunk / prefix-insert split
    programs the scheduler dispatches — engine.warmup can't derive them,
    and an unwarmed split program would compile mid-traffic."""
    _, engine = _make_engine(prefix_cache=True, prefix_stride=4)
    batcher = Batcher(engine, max_active=4, queue_size=8, prefill_chunk=chunk)
    batcher.warmup(prompt_lens=(12,))
    before = dict(engine.compile_counts)
    rng = np.random.RandomState(0)
    shared = rng.randint(0, _CFG.vocab_size, size=8)
    prompts = [
        np.concatenate(
            [shared, rng.randint(0, _CFG.vocab_size, size=4)]
        ).astype(np.int32)
        for _ in range(3)
    ]
    _run(batcher, prompts, 2)  # cold inserts, then hot resumed prefills
    _run(batcher, prompts, 2)
    assert dict(engine.compile_counts) == before


def test_incompatible_chunk_stride_rejected():
    """A chunk that is neither a multiple nor a divisor of the prefix
    stride would be silently truncated to stride alignment at every
    pre-boundary stop — the constructor must refuse it."""
    _, engine = _make_engine(prefix_cache=True, prefix_stride=4)
    with pytest.raises(ValueError, match="multiple or divisor"):
        Batcher(engine, max_active=4, queue_size=8, prefill_chunk=6)
    # multiples and divisors are fine, as is any chunk with the cache off
    Batcher(engine, max_active=4, queue_size=8, prefill_chunk=8)
    Batcher(engine, max_active=4, queue_size=8, prefill_chunk=2)
    _, plain = _make_engine(prefix_cache=False)
    Batcher(plain, max_active=4, queue_size=8, prefill_chunk=6)


def test_stop_mid_chunked_prefill_fails_fast():
    """run() exiting on the stop event must settle mid-prefill requests
    (fail fast + release their slots), not leave clients blocked on
    ``done`` until their timeout."""
    _, engine = _make_engine()
    batcher = Batcher(engine, max_active=4, queue_size=8,
                      window_ladder=(1,), prefill_chunk=4)
    free_before = engine.cache.stats()["free"]
    req = Request(np.arange(1, 17, dtype=np.int32) % 37, 2)  # 4 chunks
    batcher.submit(req)
    batcher.step()  # first chunk dispatched; request still mid-prefill
    assert req.t_first_token is None and not req.done.is_set()
    stop = threading.Event()
    stop.set()
    batcher.run(stop)
    assert req.done.is_set()
    assert req.error is not None and "stopped" in req.error
    assert engine.cache.stats()["free"] == free_before


def test_use_prefix_false_bypasses_cache():
    """A ``use_prefix=False`` request (loadgen's injected HOL probe) must
    neither query nor populate the prefix cache — probes can't evict real
    entries or skew the report's hit/miss deltas."""
    params, engine = _make_engine(prefix_cache=True, prefix_stride=4)
    batcher = Batcher(engine, max_active=4, queue_size=8)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, _CFG.vocab_size, size=12).astype(np.int32)
    req = Request(prompt, 3, use_prefix=False)
    batcher.submit(req)
    batcher.drain()
    assert req.error is None
    (ref,) = _refs(params, [prompt], 3)
    assert req.tokens == ref
    st = engine.prefix.stats()
    assert (st["hits"], st["misses"], st["inserts"], st["entries"]) == (
        0, 0, 0, 0)


def test_batcher_warmup_covers_partial_prefix_resume():
    """Longest-match lookup can resume from ANY stride multiple, not just
    boundary(t) — warmup must cover the remainder programs of those
    partial hits too, or the first such request compiles mid-traffic."""
    from lstm_tensorspark_tpu.serve.engine import GREEDY

    params, engine = _make_engine(prefix_cache=True, prefix_stride=4)
    batcher = Batcher(engine, max_active=4, queue_size=8)  # unchunked
    batcher.warmup(prompt_lens=(12,))

    # hand-plant an entry at length 4 (< boundary(12) == 8): state after
    # prompt[:4], exactly what a shorter earlier prompt would have cached
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, _CFG.vocab_size, size=12).astype(np.int32)
    slot, _ = engine.cache.acquire("seed")
    engine.prefill([(slot, slot, True, prompt[:4])], GREEDY)
    assert engine.prefix.insert(prompt[:4], slot)
    engine.cache.release("seed")

    before = dict(engine.compile_counts)
    (ref,) = _refs(params, [prompt], 3)
    assert _run(batcher, [prompt], 3) == [ref]
    assert batcher.prefix_resumed == 1
    assert dict(engine.compile_counts) == before


# ---- observability -------------------------------------------------------


def test_stats_surface_and_http_route():
    from lstm_tensorspark_tpu.serve.server import make_http_server

    _, engine = _make_engine(prefix_cache=True)
    server = ServeServer(engine, max_active=2, queue_size=4, prefill_chunk=4)
    httpd = make_http_server(server, port=0)
    host, port = httpd.server_address[:2]
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    try:
        with server:
            thread.start()
            rng = np.random.RandomState(11)
            shared = rng.randint(0, 37, size=8).astype(np.int32)
            for _ in range(2):
                p = np.concatenate(
                    [shared, rng.randint(0, 37, size=3).astype(np.int32)])
                body = json.dumps({"prompt": p.tolist(), "max_new_tokens": 2,
                                   "greedy": True}).encode()
                req = urllib.request.Request(
                    f"http://{host}:{port}/v1/generate", data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    assert r.status == 200
            with urllib.request.urlopen(
                    f"http://{host}:{port}/stats", timeout=30) as r:
                assert r.status == 200
                stats = json.loads(r.read())
            # the HTTP-level opt-out: no lookup, no insert
            p = rng.randint(0, 37, size=11).astype(np.int32)
            body = json.dumps({"prompt": p.tolist(), "max_new_tokens": 2,
                               "greedy": True, "use_prefix": False}).encode()
            req = urllib.request.Request(
                f"http://{host}:{port}/v1/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
            with urllib.request.urlopen(
                    f"http://{host}:{port}/stats", timeout=30) as r:
                stats_after = json.loads(r.read())
    finally:
        httpd.shutdown()
        httpd.server_close()
    px = stats["prefix_cache"]
    assert px["inserts"] >= 1 and px["hits"] + px["misses"] >= 2
    pxa = stats_after["prefix_cache"]
    assert (pxa["hits"] + pxa["misses"], pxa["inserts"]) == (
        px["hits"] + px["misses"], px["inserts"])
    assert "generation" in stats["cache"]
    assert any("prefill" in k for k in stats["compiles"])
    b = stats["batcher"]
    assert b["prefill_chunk"] == 4
    assert "prefill_chunks_dispatched" in b and "prefix_resumed" in b
