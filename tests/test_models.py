"""Model-family tests: bi-LSTM classifier (masking correctness, learnability)
and seq2seq forecaster (teacher-forced vs free-running, learnability)."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.models import (
    ClassifierConfig,
    Seq2SeqConfig,
    classifier_forward,
    classifier_loss,
    forecast,
    init_classifier,
    init_seq2seq,
    seq2seq_loss,
)


def test_classifier_padding_invariance():
    """Logits must not depend on tokens past each row's length."""
    cfg = ClassifierConfig(vocab_size=20, num_classes=2, hidden_size=16)
    params = init_classifier(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    tokens = rng.randint(2, 20, (3, 12)).astype(np.int32)
    lengths = np.array([5, 8, 12], np.int32)
    logits1 = classifier_forward(params, jnp.asarray(tokens), jnp.asarray(lengths), cfg)
    tokens2 = tokens.copy()
    for r, L in enumerate(lengths):
        tokens2[r, L:] = 0  # zero out padding region
    logits2 = classifier_forward(params, jnp.asarray(tokens2), jnp.asarray(lengths), cfg)
    np.testing.assert_allclose(logits1, logits2, rtol=1e-5, atol=1e-6)


def test_classifier_bidirectional_uses_both_ends():
    """Changing the FIRST token must change the logits (backward direction
    reaches t=0 through padding)."""
    cfg = ClassifierConfig(vocab_size=20, num_classes=2, hidden_size=16)
    params = init_classifier(jax.random.PRNGKey(0), cfg)
    tokens = np.full((1, 10), 3, np.int32)
    lengths = np.array([6], np.int32)
    l1 = classifier_forward(params, jnp.asarray(tokens), jnp.asarray(lengths), cfg)
    tokens2 = tokens.copy()
    tokens2[0, 0] = 7
    l2 = classifier_forward(params, jnp.asarray(tokens2), jnp.asarray(lengths), cfg)
    assert float(jnp.abs(l1 - l2).max()) > 1e-6


def test_classifier_learns_synthetic_imdb():
    import optax

    from lstm_tensorspark_tpu.data import get_dataset, padded_batches
    from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
    from lstm_tensorspark_tpu.train.loop import init_train_state

    data = get_dataset("imdb", num_examples=200, max_len=40)
    seqs, labels = data["train"]
    cfg = ClassifierConfig(
        vocab_size=len(data["vocab"]), num_classes=2, hidden_size=32
    )

    def loss_fn(params, batch, rng):
        return classifier_loss(params, batch, cfg)

    opt = make_optimizer("adam", 3e-3)
    params = init_classifier(jax.random.PRNGKey(0), cfg)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    step = make_train_step(loss_fn, opt)
    for epoch in range(6):
        for b in padded_batches(seqs, labels, 16, 40, shuffle_seed=epoch):
            state, m = step(state, b)
    _, aux = classifier_loss(state.params, next(iter(
        padded_batches(seqs, labels, 64, 40)
    )), cfg)
    assert float(aux["accuracy"]) > 0.8, float(aux["accuracy"])


def test_seq2seq_shapes_and_loss():
    cfg = Seq2SeqConfig(num_features=3, hidden_size=16, num_layers=2, horizon=5)
    params = init_seq2seq(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {
        "context": rng.randn(4, 20, 3).astype(np.float32),
        "targets": rng.randn(4, 5, 3).astype(np.float32),
    }
    loss, aux = seq2seq_loss(params, batch, cfg)
    assert np.isfinite(float(loss)) and "mae" in aux
    preds = forecast(params, jnp.asarray(batch["context"]), cfg)
    assert preds.shape == (4, 5, 3)


def test_seq2seq_learns_sine():
    from lstm_tensorspark_tpu.data.batching import forecast_windows
    from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
    from lstm_tensorspark_tpu.train.loop import init_train_state

    t = np.arange(2000, dtype=np.float32)
    series = np.stack(
        [np.sin(2 * np.pi * t / 24), np.cos(2 * np.pi * t / 24)], axis=1
    )
    cfg = Seq2SeqConfig(num_features=2, hidden_size=32, horizon=8)
    params = init_seq2seq(jax.random.PRNGKey(0), cfg)

    def loss_fn(params, batch, rng):
        return seq2seq_loss(params, batch, cfg)

    opt = make_optimizer("adam", 3e-3)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    step = make_train_step(loss_fn, opt)
    losses = []
    for i, b in enumerate(forecast_windows(series, 48, 8, 32, shuffle_seed=0)):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
        if i >= 60:
            break
    assert losses[-1] < 0.05, losses[-1]
    # free-running forecast close to ground truth on a clean periodic signal
    ctx = series[None, :48]
    preds = np.asarray(forecast(state.params, jnp.asarray(ctx), cfg))
    np.testing.assert_allclose(preds[0], series[48:56], atol=0.4)
