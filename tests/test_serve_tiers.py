"""Tiered session-state cache (serve/state_cache.py SessionTiers):
detach/restore equivalence through each tier (device↔host↔disk
round-trips must continue token-identically), eviction-during-fill and
fill-during-detach races, corrupt disk-tier files quarantined with an
honest "state lost" failure (never wrong tokens), router affinity
probing that sees host/disk-tier residency, prefix-entry spill/promote,
and the restart-resume path the serve smoke drills end to end.

The jit-touching tests share one module-scoped params + reference
program (tier-1 wall-clock discipline, same pattern as
tests/test_serve_cache.py)."""

import glob
import threading

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.obs import MetricsRegistry
from lstm_tensorspark_tpu.serve import (
    Batcher,
    Request,
    ServeEngine,
    ServeServer,
)

_CFG = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)
_PROMPT = np.array([3, 5, 7, 2, 11], np.int32)
_N_TOTAL = 10


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(0), _CFG)


@pytest.fixture(scope="module")
def ref_tokens(params):
    """Uninterrupted greedy reference: _N_TOTAL tokens for _PROMPT."""
    return np.asarray(
        make_generate_fn(_CFG, max_new_tokens=_N_TOTAL, greedy=True)(
            params, _PROMPT[None, :], jax.random.PRNGKey(0)
        )
    )[0, _PROMPT.size:]


def _engine(params, *, num_slots=2, session_dir=None, host_entries=8,
            **kw):
    return ServeEngine(
        params, _CFG, num_slots=num_slots,
        prefill_buckets=(8, 16), batch_buckets=(1, 2),
        tiered_cache=True, host_tier_entries=host_entries,
        session_dir=None if session_dir is None else str(session_dir),
        registry=MetricsRegistry(), **kw)


def _run(batcher, req):
    batcher.submit(req)
    batcher.drain()
    return req


def _evict_by_churn(batcher, sid, n=4):
    """Admit fresh kept sessions until ``sid`` is evicted off the
    device tier."""
    for i in range(n):
        _run(batcher, Request(np.array([1 + i, 2], np.int32), 1,
                              keep_session=True))
        if sid not in batcher.engine.cache:
            return
    raise AssertionError(f"{sid!r} never evicted")


# ---- round-trip equivalence through each tier -------------------------


def test_host_tier_roundtrip_token_identical(params, ref_tokens):
    """Evict a kept session into the HOST tier (async spill), continue it
    — fill + decode must equal one uninterrupted run."""
    engine = _engine(params)
    b = Batcher(engine, max_active=2, queue_size=8)
    first = _run(b, Request(_PROMPT, 4, keep_session=True))
    assert first.error is None
    sid = first.session_id
    _evict_by_churn(b, sid)
    assert engine.tiers.flush(timeout=30)
    assert engine.tiers.resident_tier(sid) == "host"
    cont = _run(b, Request(np.array([first.tokens[-1]], np.int32),
                           _N_TOTAL - 4, session_id=sid))
    assert cont.error is None
    np.testing.assert_array_equal(
        np.asarray(first.tokens + cont.tokens, np.int32), ref_tokens)
    assert engine.tiers.stats()["fills"]["host"] >= 1


def test_pending_spill_fill_before_fetch_token_identical(params, ref_tokens):
    """A continuation racing the spill worker fills straight from the
    PENDING capture (device→device, the fetch never ran) — still
    token-identical."""
    engine = _engine(params)
    b = Batcher(engine, max_active=2, queue_size=8)
    first = _run(b, Request(_PROMPT, 4, keep_session=True))
    sid = first.session_id
    # hold the worker off by filling immediately after the eviction: the
    # eviction fires inside the continuation's own admission (acquire →
    # evict LRU → fill from the just-captured pending job)
    _evict_by_churn(b, sid)
    cont = _run(b, Request(np.array([first.tokens[-1]], np.int32),
                           _N_TOTAL - 4, session_id=sid))
    assert cont.error is None
    np.testing.assert_array_equal(
        np.asarray(first.tokens + cont.tokens, np.int32), ref_tokens)


def test_disk_tier_roundtrip_token_identical(params, ref_tokens, tmp_path):
    """Force host-tier overflow to the DISK tier; the continuation fills
    from a verified disk read — token-identical."""
    engine = _engine(params, session_dir=tmp_path, host_entries=1)
    b = Batcher(engine, max_active=2, queue_size=8)
    first = _run(b, Request(_PROMPT, 4, keep_session=True))
    sid = first.session_id
    # churn enough kept sessions that sid's host entry overflows down
    for i in range(4):
        _run(b, Request(np.array([5 + i, 2, 4], np.int32), 1,
                        keep_session=True))
    assert engine.tiers.flush(timeout=30)
    # wherever it sits now (host LRU head or disk), the continuation
    # must restore it; assert the DISK tier actually got exercised
    assert engine.tiers.stats()["spills"]["disk"] >= 1
    cont = _run(b, Request(np.array([first.tokens[-1]], np.int32),
                           _N_TOTAL - 4, session_id=sid))
    assert cont.error is None
    np.testing.assert_array_equal(
        np.asarray(first.tokens + cont.tokens, np.int32), ref_tokens)


def test_restart_resume_from_disk_token_identical(params, ref_tokens,
                                                  tmp_path):
    """Serve-session checkpointing: a kept session's request-boundary
    state is write-behind checkpointed to the disk tier, and a FRESH
    engine over the same directory (the restarted server) resumes it
    token-identically."""
    engine_a = _engine(params, num_slots=4, session_dir=tmp_path)
    b_a = Batcher(engine_a, max_active=2, queue_size=8)
    first = _run(b_a, Request(_PROMPT, 4, keep_session=True))
    sid = first.session_id
    assert engine_a.tiers.flush(timeout=30)  # the durability barrier
    # "restart": a brand-new engine (empty device cache, empty host
    # tier) whose disk tier scans the same directory
    engine_b = _engine(params, num_slots=4, session_dir=tmp_path)
    b_b = Batcher(engine_b, max_active=2, queue_size=8)
    assert engine_b.tiers.resident_tier(sid) == "disk"
    cont = _run(b_b, Request(np.array([first.tokens[-1]], np.int32),
                             _N_TOTAL - 4, session_id=sid))
    assert cont.error is None
    np.testing.assert_array_equal(
        np.asarray(first.tokens + cont.tokens, np.int32), ref_tokens)
    assert engine_b.tiers.stats()["fills"]["disk"] == 1


def test_unkept_completion_discards_tier_copies(params, tmp_path):
    """A session that completes WITHOUT keep_session must not be
    resurrectable from stale tier copies — a later fill would decode
    from before the final request's tokens (wrong output)."""
    engine = _engine(params, num_slots=4, session_dir=tmp_path)
    b = Batcher(engine, max_active=2, queue_size=8)
    first = _run(b, Request(_PROMPT, 2, keep_session=True))
    sid = first.session_id
    assert engine.tiers.flush(timeout=30)
    assert engine.tiers.resident_tier(sid) == "disk"
    last = _run(b, Request(np.array([first.tokens[-1]], np.int32), 2,
                           session_id=sid))  # no keep_session
    assert last.error is None
    assert engine.tiers.resident_tier(sid) is None
    cont = _run(b, Request(np.array([1], np.int32), 2, session_id=sid))
    assert cont.error is not None and "expired" in cont.error


# ---- corruption honesty ------------------------------------------------


def test_corrupt_disk_file_quarantined_state_lost(params, tmp_path):
    """A corrupt disk-tier session file is QUARANTINED and the
    continuation fails honestly ("state lost") — never wrong tokens."""
    engine_a = _engine(params, num_slots=4, session_dir=tmp_path)
    b_a = Batcher(engine_a, max_active=2, queue_size=8)
    first = _run(b_a, Request(_PROMPT, 3, keep_session=True))
    sid = first.session_id
    assert engine_a.tiers.flush(timeout=30)
    # fresh engine = no device/host copy; then tear the file
    engine_b = _engine(params, num_slots=4, session_dir=tmp_path)
    b_b = Batcher(engine_b, max_active=2, queue_size=8)
    (path,) = glob.glob(str(tmp_path / "sess-*.state"))
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[:-4] + b"XXXX")
    cont = _run(b_b, Request(np.array([first.tokens[-1]], np.int32), 3,
                             session_id=sid))
    assert cont.error is not None and "lost" in cont.error
    assert glob.glob(str(tmp_path / "*.quarantined"))
    st = engine_b.tiers.stats()
    assert st["corrupt"] == 1 and st["fills"]["disk"] == 0


# ---- races -------------------------------------------------------------


def test_eviction_during_fill_pressure(params, ref_tokens):
    """Continuations under constant eviction pressure (slots << sessions,
    fills and evictions interleaving on every admission) stay
    token-identical — the shared-lock fill can never hand a continuation
    someone else's slot."""
    engine = _engine(params, num_slots=2, host_entries=32)
    b = Batcher(engine, max_active=2, queue_size=16)
    first = _run(b, Request(_PROMPT, 2, keep_session=True))
    sid = first.session_id
    toks = list(first.tokens)
    for _ in range(4):
        # each round: churn evicts sid, then the continuation fills it
        _evict_by_churn(b, sid)
        cont = _run(b, Request(np.array([toks[-1]], np.int32), 2,
                               session_id=sid, keep_session=True))
        assert cont.error is None
        toks.extend(cont.tokens)
    np.testing.assert_array_equal(np.asarray(toks, np.int32), ref_tokens)


def test_fill_during_detach_concurrency(params):
    """Client-thread detach/restore racing the spill worker and fills:
    every interleaving serialises on the shared cache lock, so the state
    observed after each round equals what was written."""
    engine = _engine(params, num_slots=2, host_entries=32)
    cache = engine.cache
    h = np.arange(2 * 16, dtype=np.float32).reshape(2, 16)
    state_in = None
    stop = threading.Event()
    errors = []

    def churn():
        i = 0
        while not stop.is_set():
            sid = f"churn-{i % 3}"
            if sid not in cache:
                slot, _ = cache.acquire(sid)
            i += 1

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        for round_ in range(10):
            sid = f"race-{round_}"
            # acquire+pin+write atomically under the shared cache lock —
            # the batcher gets this for free (one scheduler per cache);
            # with a concurrent acquirer, an unpinned sid can be evicted
            # between acquire and pin (the contract this test exercises,
            # not violates)
            with cache._lock:
                slot, fresh = cache.acquire(sid)
                assert fresh
                cache.pin(sid)
                cache.write_slots(np.asarray([slot]),
                                  (h + round_)[:, None, :],
                                  (-h - round_)[:, None, :])
            cache.unpin(sid)
            # evict it (churn may already have); then fill it back
            evictor = 0
            while sid in cache:
                cache.acquire(f"evictor-{round_}-{evictor}")
                evictor += 1
            with cache._lock:
                slot2, fresh2 = cache.acquire(sid)
                assert fresh2
                cache.pin(sid)  # hold it across fill → detach
                filled = engine.tiers.fill(sid, slot2)
            if not filled:
                errors.append(f"round {round_}: state lost")
                cache.release(sid)
                continue
            state_in = cache.detach(sid)  # fill-during-detach round-trip
            np.testing.assert_array_equal(state_in.h, h + round_)
            np.testing.assert_array_equal(state_in.c, -h - round_)
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors, errors


# ---- router integration ------------------------------------------------


def test_router_affinity_sees_tier_residency(params, ref_tokens):
    """A continuation of a session spilled off its replica's device slots
    routes HOME via the tier-residency probe (not to the least-loaded
    replica, which would fail it "unknown session") and decodes
    token-identically."""
    reg = MetricsRegistry()
    engines = [
        ServeEngine(params, _CFG, num_slots=3, prefill_buckets=(8, 16),
                    batch_buckets=(1, 2), rng_seed=i, registry=reg,
                    tiered_cache=True, host_tier_entries=16, replica=i)
        for i in range(2)
    ]
    server = ServeServer(engines, max_active=2, queue_size=16)
    with server:
        first = server.generate(_PROMPT, max_new_tokens=4,
                                keep_session=True)
        sid, home = first.session_id, first.replica
        homecache = server.replicas[home].engine.cache
        for i in range(16):
            server.generate([2 + i % 5, 3], max_new_tokens=1,
                            keep_session=True)
            if sid not in homecache:
                break
        assert sid not in homecache, "session never evicted"
        assert server.replicas[home].engine.tiers.has(sid)
        cont = server.generate([first.tokens[-1]],
                               max_new_tokens=_N_TOTAL - 4,
                               session_id=sid, keep_session=True)
        assert cont.replica == home
        np.testing.assert_array_equal(
            np.asarray(list(first.tokens) + list(cont.tokens), np.int32),
            ref_tokens)
        assert server.replicas[home].engine.tiers.stats()[
            "fills"]["host"] >= 1


# ---- prefix-entry spill / promote --------------------------------------


def test_prefix_entry_spills_and_promotes(params, ref_tokens):
    """With tiers attached, a state-cache eviction of a prefix entry's
    backing slot SPILLS the entry (state kept in the host tier) instead
    of invalidating it; the next lookup promotes it back for one
    host→device copy and the resumed prefill stays token-identical."""
    engine = ServeEngine(
        params, _CFG, num_slots=3, prefill_buckets=(8, 16),
        batch_buckets=(1, 2), prefix_cache=True, prefix_stride=2,
        prefix_entries=4, tiered_cache=True, host_tier_entries=16,
        registry=MetricsRegistry())
    b = Batcher(engine, max_active=2, queue_size=8)
    p1 = _run(b, Request(_PROMPT, 2))
    assert engine.prefix.stats()["inserts"] >= 1
    # slot pressure evicts the prefix backing slot → spill, not invalidate
    for i in range(4):
        _run(b, Request(np.array([1 + i, 2 + i], np.int32), 1,
                        keep_session=True))
    st = engine.prefix.stats()
    assert st["spilled"] >= 1 and st["invalidated"] == 0, st
    p2 = _run(b, Request(_PROMPT, 2))
    st = engine.prefix.stats()
    assert st["promoted"] >= 1 and st["hits"] >= 1, st
    assert p1.tokens == p2.tokens
    np.testing.assert_array_equal(np.asarray(p2.tokens), ref_tokens[:2])


def test_shared_dir_file_written_after_scan_is_visible(params, tmp_path,
                                                       ref_tokens):
    """Two replicas share one --session-dir: a session file written by
    replica A AFTER replica B's startup scan must still be fillable on B
    (deterministic filename → one stat on index miss). This is what
    makes retirement's evacuate-to-shared-disk migration — and mixed
    restart topologies — actually serve."""
    engine_b = _engine(params, num_slots=4, session_dir=tmp_path)
    b_b = Batcher(engine_b, max_active=2, queue_size=8)
    # A starts later and checkpoints a session B's scan never saw
    engine_a = _engine(params, num_slots=4, session_dir=tmp_path)
    b_a = Batcher(engine_a, max_active=2, queue_size=8)
    first = _run(b_a, Request(_PROMPT, 4, keep_session=True))
    sid = first.session_id
    assert engine_a.tiers.flush(timeout=30)
    assert engine_b.tiers.resident_tier(sid) == "disk"
    cont = _run(b_b, Request(np.array([first.tokens[-1]], np.int32),
                             _N_TOTAL - 4, session_id=sid))
    assert cont.error is None
    np.testing.assert_array_equal(
        np.asarray(first.tokens + cont.tokens, np.int32), ref_tokens)


# ---- plumbing ----------------------------------------------------------


def test_tier_metrics_and_stats_surfaces(params, tmp_path):
    """Tier counters flow into the registry (replica-labelled families)
    and engine.stats()['tiers']; ServeServer.stop() flushes the
    write-behind checkpoints."""
    reg = MetricsRegistry()
    engine = ServeEngine(
        params, _CFG, num_slots=2, prefill_buckets=(8, 16),
        batch_buckets=(1, 2), tiered_cache=True, host_tier_entries=8,
        session_dir=str(tmp_path), registry=reg)
    server = ServeServer(engine, max_active=2, queue_size=8)
    with server:
        first = server.generate(_PROMPT, max_new_tokens=2,
                                keep_session=True)
        for i in range(4):
            server.generate([4 + i, 2], max_new_tokens=1,
                            keep_session=True)
    # stop() flushed: the kept sessions' checkpoints are on disk
    assert glob.glob(str(tmp_path / "sess-*.state"))
    ts = engine.stats()["tiers"]
    assert ts["spills"]["disk"] >= 1 and ts["spills"]["host"] >= 1
    text = reg.render_prometheus()
    assert "serve_tier_spills_total" in text
    assert 'replica="0"' in text
    # the session survives in some tier after all that churn
    assert engine.tiers.has(first.session_id)


# ---- batched admission fills (SessionTiers.fill_batch) -----------------


def test_fill_batch_token_identical_vs_per_session(params, ref_tokens):
    """One batched restore must hand back EXACTLY the states the
    per-session fill path would: spill a set of sessions, restore half
    through fill() and half through one fill_batch(), detach and compare
    bit-for-bit — then prove the decode continuation through the batched
    admission path matches the uninterrupted reference."""
    engine = _engine(params, num_slots=8, host_entries=32)
    cache = engine.cache
    h = np.arange(2 * 16, dtype=np.float32).reshape(2, 16)
    sids = [f"fb-{i}" for i in range(6)]
    for i, sid in enumerate(sids):
        with cache._lock:
            slot, _ = cache.acquire(sid)
            cache.write_slots(np.asarray([slot]), (h + i)[:, None, :],
                              (-h - i)[:, None, :])
    for i in range(8):  # churn every slot: all six sids spill
        cache.acquire(f"churn-{i}")
    for sid in sids:
        assert sid not in cache
    engine.tiers.flush(timeout=10)

    def restore(sid):
        with cache._lock:
            slot, fresh = cache.acquire(sid)
            assert fresh
            cache.pin(sid)
        return slot

    # per-session path
    single = {}
    for sid in sids[:3]:
        slot = restore(sid)
        assert engine.tiers.fill(sid, slot)
        single[sid] = cache.detach(sid)
    # batched path — ONE call for the remaining three
    pairs = [(sid, restore(sid)) for sid in sids[3:]]
    res = engine.tiers.fill_batch(pairs)
    assert res == {sid: True for sid in sids[3:]}
    for i, sid in enumerate(sids):
        st = single[sid] if i < 3 else cache.detach(sid)
        np.testing.assert_array_equal(st.h, h + i)
        np.testing.assert_array_equal(st.c, -h - i)

    # and through the scheduler: a kept session evicted + continued via
    # batched admission decodes token-identically to the reference
    b = Batcher(engine, max_active=2, queue_size=16)
    first = _run(b, Request(_PROMPT, 2, keep_session=True))
    toks = list(first.tokens)
    _evict_by_churn(b, first.session_id, n=10)  # 8 slots to churn through
    cont = _run(b, Request(np.array([toks[-1]], np.int32), _N_TOTAL - 2,
                           session_id=first.session_id))
    assert cont.error is None
    toks.extend(cont.tokens)
    np.testing.assert_array_equal(np.asarray(toks, np.int32), ref_tokens)


def test_eviction_during_batched_fill_pressure(params):
    """The eviction-during-fill pressure loop re-run against the BATCHED
    path: several kept sessions continued in the SAME admission batch
    under slots << sessions churn — every continuation fills from its
    own tier copy, token-identical per session, no cross-session slot
    aliasing."""
    engine = _engine(params, num_slots=4, host_entries=32)
    b = Batcher(engine, max_active=4, queue_size=16)
    prompts = {f"p{i}": np.array([3 + i, 5, 7 + i], np.int32)
               for i in range(3)}
    refs, sids, lasts = {}, {}, {}
    for name, p in prompts.items():
        refs[name] = np.asarray(
            make_generate_fn(_CFG, max_new_tokens=8, greedy=True)(
                params, p[None, :], jax.random.PRNGKey(0)))[0, p.size:]
        first = _run(b, Request(p, 2, keep_session=True))
        sids[name] = first.session_id
        lasts[name] = list(first.tokens)
    for round_ in range(3):
        # churn every session out of the device tier...
        for i in range(6):
            _run(b, Request(np.array([1 + i, 2], np.int32), 1,
                            keep_session=True))
        fills_before = engine.tiers.stats()["fills"]["host"]
        # ...then submit ALL continuations before draining: one _admit
        # pass restores them in one fill_batch call
        reqs = {}
        for name in prompts:
            reqs[name] = Request(
                np.array([lasts[name][-1]], np.int32), 2,
                session_id=sids[name], keep_session=True)
            b.submit(reqs[name])
        b.drain()
        for name in prompts:
            assert reqs[name].error is None, (round_, name,
                                              reqs[name].error)
            lasts[name].extend(reqs[name].tokens)
        assert engine.tiers.stats()["fills"]["host"] > fills_before
    for name in prompts:
        np.testing.assert_array_equal(
            np.asarray(lasts[name], np.int32), refs[name])


def test_fill_batch_during_detach_concurrency(params):
    """The fill-during-detach race re-run against fill_batch: the
    batched restore's bookkeeping holds the shared cache lock, so a
    concurrent detach/churn interleaving still observes exactly the
    written state."""
    engine = _engine(params, num_slots=2, host_entries=32)
    cache = engine.cache
    h = np.arange(2 * 16, dtype=np.float32).reshape(2, 16)
    stop = threading.Event()
    errors = []

    def churn():
        i = 0
        while not stop.is_set():
            sid = f"churn-{i % 3}"
            if sid not in cache:
                cache.acquire(sid)
            i += 1

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        for round_ in range(10):
            sid = f"race-{round_}"
            with cache._lock:
                slot, fresh = cache.acquire(sid)
                assert fresh
                cache.pin(sid)
                cache.write_slots(np.asarray([slot]),
                                  (h + round_)[:, None, :],
                                  (-h - round_)[:, None, :])
            cache.unpin(sid)
            evictor = 0
            while sid in cache:
                cache.acquire(f"evictor-{round_}-{evictor}")
                evictor += 1
            with cache._lock:
                slot2, fresh2 = cache.acquire(sid)
                assert fresh2
                cache.pin(sid)
            filled = engine.tiers.fill_batch([(sid, slot2)])
            if not filled.get(sid):
                errors.append(f"round {round_}: state lost")
                cache.release(sid)
                continue
            state_in = cache.detach(sid)
            np.testing.assert_array_equal(state_in.h, h + round_)
            np.testing.assert_array_equal(state_in.c, -h - round_)
    finally:
        stop.set()
        t.join(timeout=10)
    assert not errors, errors


def test_fill_batch_mixed_sources_and_misses(params, tmp_path):
    """One batch mixing a pending capture, a host-tier state, a
    disk-tier state and an unknown sid: each fills from its own source,
    the miss is reported False and counted, and the batch's scatter
    never touches the missing session's slot (still fresh-zero)."""
    engine = _engine(params, num_slots=8, host_entries=32,
                     session_dir=tmp_path)
    cache = engine.cache
    tiers = engine.tiers
    h = np.arange(2 * 16, dtype=np.float32).reshape(2, 16)
    # three sessions with distinct states, spilled at different depths
    for i, sid in enumerate(("s-pend", "s-host", "s-disk")):
        with cache._lock:
            slot, _ = cache.acquire(sid)
            cache.write_slots(np.asarray([slot]), (h + i)[:, None, :],
                              (h - i)[:, None, :])
    for i in range(8):
        cache.acquire(f"churn-{i}")
    tiers.flush(timeout=10)  # everything fetched to host
    # s-disk: force down to disk only
    st = tiers._host.pop("s-disk")
    tiers._disk.put("s-disk", st)
    # s-pend: re-insert + evict WITHOUT letting the worker fetch, so the
    # fill must come from the pending capture's device handles
    with cache._lock:
        slot, _ = cache.acquire("s-pend")
        cache.write_slots(np.asarray([slot]), (h + 10)[:, None, :],
                          (h - 10)[:, None, :])
        tiers._host.pop("s-pend", None)
        for i in range(8):
            cache.acquire(f"churn-z{i}")
    assert tiers._pending.get("s-pend") is not None

    pairs = []
    for sid in ("s-pend", "s-host", "s-disk", "s-missing"):
        with cache._lock:
            slot, fresh = cache.acquire(sid)
            assert fresh
            cache.pin(sid)
        pairs.append((sid, slot))
    misses_before = tiers.stats()["misses"]
    res = tiers.fill_batch(pairs)
    assert res == {"s-pend": True, "s-host": True, "s-disk": True,
                   "s-missing": False}
    assert tiers.stats()["misses"] == misses_before + 1
    exp = {"s-pend": (h + 10, h - 10), "s-host": (h + 1, h - 1),
           "s-disk": (h + 2, h - 2)}
    for sid, (eh, ec) in exp.items():
        st = cache.detach(sid)
        np.testing.assert_array_equal(st.h, eh)
        np.testing.assert_array_equal(st.c, ec)
    # the missing sid's pinned slot was never written by the batch
    st = cache.detach("s-missing")
    np.testing.assert_array_equal(st.h, np.zeros((2, 16), np.float32))
