"""Checkpoint hardening (train/checkpoint.py): sha256 sidecars, fsync'd
atomic writes, and restore_latest's quarantine-and-fall-back path — a
truncated or bit-flipped newest checkpoint must cost one checkpoint
interval, never the run."""

import os

import jax
import jax.numpy as jnp
import optax
import pytest

from lstm_tensorspark_tpu.resilience import faults
from lstm_tensorspark_tpu.train.checkpoint import Checkpointer
from lstm_tensorspark_tpu.train.loop import init_train_state


@pytest.fixture(autouse=True)
def _clean_plane():
    faults.disarm()
    yield
    faults.disarm()


def _state(step: int, w: float):
    opt = optax.sgd(0.1)
    s = init_train_state({"w": jnp.full((4,), w, jnp.float32)}, opt,
                         jax.random.PRNGKey(0))
    return s._replace(step=jnp.asarray(step, jnp.int32))


def _save_steps(ckpt, steps):
    for i, step in enumerate(steps):
        ckpt.save(_state(step, float(i + 1)))


def test_sidecar_written_and_restore_verifies(tmp_path):
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2])
    assert os.path.exists(tmp_path / "step_2.msgpack.sha256")
    restored = ckpt.restore_latest(_state(0, 0.0))
    assert int(restored.step) == 2


def test_truncated_newest_falls_back_and_quarantines(tmp_path):
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2, 4])
    path = tmp_path / "step_4.msgpack"
    data = path.read_bytes()
    path.write_bytes(data[: len(data) // 2])  # torn write

    restored = ckpt.restore_latest(_state(0, 0.0))
    assert int(restored.step) == 2  # fell back to the newest VALID step
    assert float(restored.params["w"][0]) == pytest.approx(1.0)
    assert os.path.exists(tmp_path / "step_4.msgpack.quarantined")
    assert not os.path.exists(tmp_path / "step_4.msgpack")
    # the fallback is durable: a SECOND restore sees step 2 directly
    again = ckpt.restore_latest(_state(0, 0.0))
    assert int(again.step) == 2


def test_single_bit_flip_detected_by_checksum(tmp_path):
    """msgpack may happily parse a bit-flipped file — the sidecar is what
    catches silent corruption, not the parser."""
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2, 4])
    path = tmp_path / "step_4.msgpack"
    raw = bytearray(path.read_bytes())
    raw[len(raw) // 2] ^= 0x01
    path.write_bytes(bytes(raw))
    restored = ckpt.restore_latest(_state(0, 0.0))
    assert int(restored.step) == 2


def test_all_corrupt_returns_none(tmp_path):
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2])
    (tmp_path / "step_2.msgpack").write_bytes(b"garbage")
    assert ckpt.restore_latest(_state(0, 0.0)) is None


def test_legacy_checkpoint_without_sidecar_still_restores(tmp_path):
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2])
    os.remove(tmp_path / "step_2.msgpack.sha256")  # pre-checksum era file
    restored = ckpt.restore_latest(_state(0, 0.0))
    assert int(restored.step) == 2


def test_cleanup_removes_sidecars_with_payloads(tmp_path):
    ckpt = Checkpointer(str(tmp_path), keep=2)
    _save_steps(ckpt, [2, 4, 6, 8])
    names = set(os.listdir(tmp_path))
    assert "step_2.msgpack" not in names and "step_4.msgpack" not in names
    assert not any(n.startswith("step_2.") or n.startswith("step_4.")
                   for n in names), names  # no orphaned sidecars
    assert {"step_6.msgpack.sha256", "step_8.msgpack.sha256"} <= names


def test_injected_ckpt_corrupt_fault_roundtrip(tmp_path):
    """The chaos path end to end in-process: the armed fault tears the
    step-4 file right after save; restore quarantines it and falls back."""
    faults.arm("ckpt_corrupt@4", state_dir=str(tmp_path))
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2, 4])
    restored = ckpt.restore_latest(_state(0, 0.0))
    assert int(restored.step) == 2
    assert os.path.exists(tmp_path / "step_4.msgpack.quarantined")
    # one-shot: re-saving step 4 is clean and restorable
    ckpt.save(_state(4, 9.0))
    assert int(ckpt.restore_latest(_state(0, 0.0)).step) == 4


def test_config_mismatch_raises_instead_of_quarantining(tmp_path):
    """A checksum-VERIFIED file that fails to deserialize means the
    TEMPLATE is wrong (changed model config), not the file: restore must
    surface that loudly, never quarantine every checkpoint and silently
    restart from step 0."""
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2])
    opt = optax.sgd(0.1)
    wrong_template = init_train_state(
        {"w": jnp.zeros((4,)), "extra": jnp.zeros((3,))}, opt,
        jax.random.PRNGKey(0))
    with pytest.raises(Exception) as ei:
        ckpt.restore_latest(wrong_template)
    assert "Quarantin" not in str(ei.value)
    assert os.path.exists(tmp_path / "step_2.msgpack")  # untouched


def test_unrenamable_quarantine_still_terminates(tmp_path, monkeypatch):
    """Read-only checkpoint dir: the quarantine rename fails, but each step
    is attempted at most once per call, so restore_latest returns instead
    of spinning on the same corrupt newest forever."""
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2])
    (tmp_path / "step_2.msgpack").write_bytes(b"garbage")
    monkeypatch.setattr(Checkpointer, "_quarantine_step",
                        lambda self, step, reason: None)  # rename impossible
    assert ckpt.restore_latest(_state(0, 0.0)) is None  # terminates


def test_transient_io_error_not_quarantined(tmp_path, monkeypatch):
    """OSError during the read is transient IO, not corruption: it must
    propagate (retry territory), not destroy checkpoint discoverability."""
    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2])
    monkeypatch.setattr(
        Checkpointer, "_read_verified",
        staticmethod(lambda path: (_ for _ in ()).throw(OSError("EIO"))))
    with pytest.raises(OSError):
        ckpt.restore_latest(_state(0, 0.0))
    assert os.path.exists(tmp_path / "step_2.msgpack")  # untouched


def test_corrupt_sharded_best_returns_none(tmp_path):
    """A sharded best set with a missing/corrupt proc file must quarantine
    and report 'no best', not crash a --resume-best run."""
    import json as _json

    ckpt = Checkpointer(str(tmp_path), keep=5)
    (tmp_path / "best.complete").write_text(
        _json.dumps({"writers": 2, "step": 2, "value": 1.0}))
    (tmp_path / "best_2.proc0.msgpack").write_bytes(b"x")  # proc1 missing
    assert ckpt.restore_best(_state(0, 0.0)) is None
    assert os.path.exists(tmp_path / "best.complete.quarantined")


def test_overwrite_crash_never_pairs_new_bytes_with_old_hash(tmp_path,
                                                             monkeypatch):
    """Crash between the payload rename and the sidecar write of an
    OVERWRITTEN path (best.msgpack): the old sidecar must already be gone,
    leaving a sidecar-less payload (legacy-accepted) — never a stale-hash
    pair that falsely quarantines a valid best."""
    ckpt = Checkpointer(str(tmp_path), keep=5)
    ckpt.save_best(_state(2, 1.0), value=2.0)
    orig_replace = os.replace

    def crashing_replace(src, dst):
        orig_replace(src, dst)
        if str(dst).endswith("best.msgpack"):
            raise KeyboardInterrupt  # "crash" right after payload visible

    monkeypatch.setattr(os, "replace", crashing_replace)
    with pytest.raises(KeyboardInterrupt):
        ckpt.save_best(_state(4, 9.0), value=1.0)
    monkeypatch.setattr(os, "replace", orig_replace)

    fresh = Checkpointer(str(tmp_path), keep=5)
    assert fresh.best_meta() == {"step": 4, "value": 1.0}  # not quarantined
    assert not os.path.exists(tmp_path / "best.msgpack.quarantined")
    restored = fresh.restore_best(_state(0, 0.0))
    assert int(restored.step) == 4


def test_resume_best_corrupt_aborts_before_fencing(tmp_path):
    """--resume-best with a corrupt best: restore_best returns None (new
    quarantine contract) and the CLI must abort BEFORE fence_after — the
    fence would delete the run's valid newer step checkpoints."""
    import argparse
    import json as _json

    from lstm_tensorspark_tpu.cli import _wire_checkpoint

    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2, 4, 6])
    # corrupt SHARDED best at step 2: marker claims 2 writers, 1 present
    (tmp_path / "best.complete").write_text(
        _json.dumps({"writers": 2, "step": 2, "value": 1.0}))
    (tmp_path / "best_2.proc0.msgpack").write_bytes(b"x")
    args = argparse.Namespace(checkpoint_dir=str(tmp_path), resume_best=True,
                              resume=False, async_checkpoint=False)

    class _Logger:
        def log(self, record):
            pass

    with pytest.raises(SystemExit, match="corrupt"):
        _wire_checkpoint(args, _Logger(), lambda: _state(0, 0.0))
    # the abandoned-lineage fence never ran: newer steps survive
    assert os.path.exists(tmp_path / "step_4.msgpack")
    assert os.path.exists(tmp_path / "step_6.msgpack")


def test_resume_all_corrupt_aborts_instead_of_fresh_start(tmp_path):
    """--resume where checkpoints EXIST but all fail verification: the run
    must abort loudly, not silently re-init from step 0 and discard the
    run's progress (an empty dir stays a legitimate fresh start)."""
    import argparse

    from lstm_tensorspark_tpu.cli import _wire_checkpoint

    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2])
    (tmp_path / "step_2.msgpack").write_bytes(b"garbage")
    args = argparse.Namespace(checkpoint_dir=str(tmp_path), resume_best=False,
                              resume=True, async_checkpoint=False)

    class _Logger:
        def log(self, record):
            pass

    with pytest.raises(SystemExit, match="failed verification"):
        _wire_checkpoint(args, _Logger(), lambda: _state(0, 0.0))
    # the refusal PERSISTS across a supervisor relaunch: the quarantine
    # above left no valid checkpoints (has_checkpoint is now False), and
    # the relaunch must NOT silently fresh-start from step 0
    with pytest.raises(SystemExit, match="quarantined"):
        _wire_checkpoint(args, _Logger(), lambda: _state(0, 0.0))


def test_serve_refuses_fully_corrupt_checkpoint_dir(tmp_path):
    """cli serve with a checkpoint dir whose only checkpoint is corrupt:
    restore_latest quarantines it and returns None — serve must refuse
    loudly instead of crashing (or silently serving random init)."""
    from lstm_tensorspark_tpu.cli import main as cli_main

    ckpt = Checkpointer(str(tmp_path), keep=5)
    _save_steps(ckpt, [2])
    (tmp_path / "step_2.msgpack").write_bytes(b"garbage")
    with pytest.raises(SystemExit, match="corrupt"):
        cli_main(["serve", "--selftest", "--checkpoint-dir", str(tmp_path)])


def test_corrupt_best_is_quarantined_not_fatal(tmp_path):
    ckpt = Checkpointer(str(tmp_path), keep=5)
    ckpt.save_best(_state(6, 3.0), value=1.25)
    assert ckpt.best_meta() == {"step": 6, "value": 1.25}
    best = tmp_path / "best.msgpack"
    best.write_bytes(best.read_bytes()[:32])
    fresh = Checkpointer(str(tmp_path), keep=5)  # no meta cache
    assert fresh.best_meta() is None
    assert fresh.restore_best(_state(0, 0.0)) is None
    assert os.path.exists(tmp_path / "best.msgpack.quarantined")
