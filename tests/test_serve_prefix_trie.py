"""Prefix-state fabric (serve/prefix_trie.py: radix trie of recurrent
carries, tiered spill, cross-replica propagation).

The ISSUE-19 acceptance surface:

- longest-match correctness: ``PrefixTrie.lookup`` returns the deepest
  stateful node on the prompt's path, capped at ``len(prompt) - 1``,
  exactly matching a brute-force longest-common-prefix reference over
  randomized token sets (including interior nodes created by edge
  splits);
- leaf-first LRU eviction: capacity pressure evicts zero-ref LEAVES
  before interior nodes with live descendants, and ref-held (pinned)
  nodes are never evicted;
- tiered spill/promote: a slot eviction spills the node's state into
  the host tier and a later lookup promotes it back bit-identically;
  the configurable host-byte bound evicts the coldest spilled node;
- cross-replica propagation: the propagator worker posts inserted
  nodes to peers, ``adopt_remote`` is idempotent by token path AND by
  recently-applied hash (at-least-once replay), rejects off-stride or
  wrong-shape payloads, and skips circuit-suspect peers;
- PARITY: greedy generation through a ``prefix_fabric=True`` engine +
  batcher (scan and Pallas decode kernels, chunked and monolithic
  prefill) is token-identical to models/generate.py, cold and hot.

Parity stacks build their own engines; the configs are tiny so each
XLA compile is subsecond on CPU.
"""

import json
import threading
import time
import urllib.request

import jax
import numpy as np

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.serve import (
    Batcher,
    PrefixPropagator,
    PrefixTrie,
    Request,
    ServeEngine,
    ServeServer,
    SessionTiers,
    StateCache,
)
from lstm_tensorspark_tpu.serve.prefix_trie import decode_propagated_state
from lstm_tensorspark_tpu.serve.state_cache import DetachedState

_CFG = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)


def _make_engine(**kw):
    params = init_lm(jax.random.PRNGKey(0), _CFG)
    kw.setdefault("num_slots", 16)
    kw.setdefault("prefill_buckets", (4, 8, 16))
    kw.setdefault("batch_buckets", (1, 2, 4))
    return params, ServeEngine(params, _CFG, **kw)


def _refs(params, prompts, n_new):
    gen = make_generate_fn(_CFG, max_new_tokens=n_new, greedy=True)
    return [
        np.asarray(gen(params, p[None, :], jax.random.PRNGKey(0)))[
            0, p.size:].tolist()
        for p in prompts
    ]


def _run(batcher, prompts, n_new):
    reqs = [Request(p, n_new) for p in prompts]
    for r in reqs:
        batcher.submit(r)
    batcher.drain()
    assert all(r.error is None for r in reqs), [r.error for r in reqs]
    return [r.tokens for r in reqs]


def _seeded(num_layers=2, hidden=4, num_slots=8, **trie_kw):
    """A small cache + trie + one pinned seed slot holding a
    distinctive state (h = +arange, c = -arange per layer)."""
    cache = StateCache(num_layers=num_layers, num_slots=num_slots,
                       hidden_size=hidden)
    trie_kw.setdefault("stride", 2)
    trie_kw.setdefault("max_nodes", 8)
    trie_kw.setdefault("host_bytes", 1 << 20)
    trie = PrefixTrie(cache, **trie_kw)
    slot, _ = cache.acquire_pinned("seed")
    h = np.arange(num_layers * hidden, dtype=np.float32).reshape(
        num_layers, 1, hidden)
    cache.write_slots(np.asarray([slot]), h, -h)
    return cache, trie, slot, (h[:, 0, :], -h[:, 0, :])


# ---- longest-match correctness ------------------------------------------


def test_longest_match_vs_bruteforce_randomized():
    """Random stride-aligned inserts over a 4-token alphabet (maximal
    prefix sharing → lots of edge splits), then random lookups checked
    against a brute-force longest-prefix-over-inserted-keys reference
    capped at len(prompt) - 1."""
    rng = np.random.RandomState(0)
    cache = StateCache(num_layers=1, num_slots=300, hidden_size=4)
    trie = PrefixTrie(cache, stride=2, max_nodes=256, host_bytes=1 << 20)
    slot, _ = cache.acquire_pinned("seed")
    keys = set()
    for _ in range(120):
        length = 2 * int(rng.randint(1, 8))
        toks = tuple(int(t) for t in rng.randint(0, 4, size=length))
        trie.insert(np.asarray(toks, np.int32), slot)
        keys.add(toks)   # insert() returning False here can only be dedup
    assert len(trie) == len(keys)
    for _ in range(300):
        plen = int(rng.randint(1, 17))
        prompt = rng.randint(0, 4, size=plen).astype(np.int32)
        node, matched = trie.lookup(prompt)
        want = max(
            (len(k) for k in keys
             if len(k) <= plen - 1 and tuple(prompt[:len(k)]) == k),
            default=0)
        assert matched == want, (prompt.tolist(), matched, want)
        if node is not None:
            assert node.length == matched
            trie.release(node)
        else:
            assert want == 0


def test_interior_insert_splits_edge():
    """Inserting a shorter key AFTER a longer one splits the existing
    edge: both depths must then match, and the full-prompt cap (>= 1
    token must remain to prefill) still binds."""
    cache, trie, slot, _ = _seeded()
    assert trie.insert(np.array([1, 2, 3, 4], np.int32), slot)
    assert trie.insert(np.array([1, 2], np.int32), slot)   # splits [1,2,3,4]
    node, n = trie.lookup(np.array([1, 2, 9], np.int32))
    assert node is not None and n == 2
    trie.release(node)
    node, n = trie.lookup(np.array([1, 2, 3, 4, 9], np.int32))
    assert node is not None and n == 4
    trie.release(node)
    # cap: matched length never covers the FULL prompt
    node, n = trie.lookup(np.array([1, 2, 3, 4], np.int32))
    assert node is not None and n == 2
    trie.release(node)
    st = trie.stats()
    assert st["entries"] == 2 and st["nodes_device"] == 2
    assert st["misses"] == 0


# ---- leaf-first eviction + refcount pins --------------------------------


def test_leaf_first_eviction_and_refcount_pins():
    cache = StateCache(num_layers=1, num_slots=12, hidden_size=4)
    trie = PrefixTrie(cache, stride=2, max_nodes=3, host_bytes=1 << 20)
    slot, _ = cache.acquire_pinned("seed")
    assert trie.insert(np.array([1, 2], np.int32), slot)
    assert trie.insert(np.array([1, 2, 3, 4], np.int32), slot)
    assert trie.insert(np.array([1, 2, 5, 6], np.int32), slot)
    # capacity pressure: the victim must be the LRU zero-ref LEAF
    # ([1,2,3,4]) — NOT the interior [1,2], which has live descendants
    assert trie.insert(np.array([7, 8], np.int32), slot)
    node, n = trie.lookup(np.array([1, 2, 3, 4, 9], np.int32))
    assert n == 2 and node.length == 2   # fell back to the interior node
    trie.release(node)
    node, n = trie.lookup(np.array([1, 2, 5, 6, 9], np.int32))
    assert n == 4
    # hold the ref: [1,2,5,6] is now pinned and must survive eviction
    assert trie.insert(np.array([9, 9], np.int32), slot)    # evicts [7,8]
    assert trie.insert(np.array([11, 12], np.int32), slot)  # evicts [9,9]
    held, m = trie.lookup(np.array([1, 2, 5, 6, 0], np.int32))
    assert m == 4 and held is node
    trie.release(held)
    trie.release(node)
    st = trie.stats()
    assert st["evictions"] >= 3 and st["entries"] == 3
    # all nodes ref-held -> insert degrades to False, never raises
    holds = [trie.lookup(np.array(list(k) + [0], np.int32))
             for k in ([1, 2], [1, 2, 5, 6], [11, 12])]
    assert all(h[0] is not None for h in holds)
    assert not trie.insert(np.array([13, 14], np.int32), slot)
    for h, _ in holds:
        trie.release(h)
    assert trie.insert(np.array([13, 14], np.int32), slot)


# ---- tiered spill / promote ---------------------------------------------


def test_spill_promote_roundtrip_identity():
    """Slot pressure spills a trie node into the host tier; the next
    lookup promotes it back into a fresh slot BIT-IDENTICALLY."""
    cache = StateCache(num_layers=2, num_slots=4, hidden_size=4)
    tiers = SessionTiers(cache, host_entries=8)
    trie = PrefixTrie(cache, stride=2, max_nodes=8, host_bytes=1 << 20,
                      tiers=tiers)
    try:
        slot, _ = cache.acquire_pinned("seed")
        h = np.arange(8, dtype=np.float32).reshape(2, 1, 4)
        cache.write_slots(np.asarray([slot]), h, -h)
        assert trie.insert(np.array([1, 2], np.int32), slot)
        # pin enough sessions to evict the (unpinned) prefix slot
        cache.acquire_pinned("a")
        cache.acquire_pinned("b")
        cache.acquire_pinned("c")
        st = trie.stats()
        assert st["nodes_spilled"] == 1 and st["spilled"] == 1
        assert st["spilled_bytes"] == st["state_bytes"]
        cache.release("a")   # make a slot reclaimable for the promote
        node, n = trie.lookup(np.array([1, 2, 9], np.int32))
        assert node is not None and n == 2 and node.slot is not None
        np.testing.assert_array_equal(
            np.asarray(cache.h[:, node.slot, :]), h[:, 0, :])
        np.testing.assert_array_equal(
            np.asarray(cache.c[:, node.slot, :]), -h[:, 0, :])
        trie.release(node)
        st = trie.stats()
        assert st["promoted"] == 1 and st["nodes_spilled"] == 0
    finally:
        tiers.close()


def test_host_byte_bound_evicts_coldest_spilled():
    """``host_bytes`` bounds SPILLED trie state: overflow evicts the
    coldest spilled zero-ref node instead of growing without bound."""
    cache = StateCache(num_layers=1, num_slots=4, hidden_size=4)
    tiers = SessionTiers(cache, host_entries=8)
    # state_bytes = 2 * 1 * 4 * 4 = 32 -> bound admits exactly ONE
    # spilled node
    trie = PrefixTrie(cache, stride=2, max_nodes=8, host_bytes=32,
                      tiers=tiers)
    try:
        slot, _ = cache.acquire_pinned("seed")
        assert trie.insert(np.array([1, 2], np.int32), slot)
        assert trie.insert(np.array([3, 4], np.int32), slot)
        cache.acquire_pinned("a")   # takes the last free slot
        cache.acquire_pinned("b")   # spills [1,2] (LRU): 32 <= 32, kept
        cache.acquire_pinned("c")   # spills [3,4]: 64 > 32 -> evict [1,2]
        st = trie.stats()
        assert st["nodes_spilled"] == 1 and st["entries"] == 1
        assert st["spilled_bytes"] <= st["host_bytes"]
        node, n = trie.lookup(np.array([1, 2, 9], np.int32))
        assert node is None and n == 0   # the cold node is honestly gone
        cache.release("a")
        node, n = trie.lookup(np.array([3, 4, 9], np.int32))
        assert node is not None and n == 2   # the hot one promotes
        trie.release(node)
    finally:
        tiers.close()


# ---- cross-replica propagation ------------------------------------------


class _FakeTransport:
    def __init__(self):
        self.posts = []

    def rpc_post(self, path, body, **kw):
        self.posts.append((path, json.loads(json.dumps(body)), kw))
        return {"applied": 1}


class _FakePeer:
    def __init__(self):
        self.transport = _FakeTransport()
        self.suspected = False

    def suspect(self):
        return self.suspected


def test_propagation_roundtrip_dedup_and_rejection():
    cache_a, trie_a, slot_a, (h0, c0) = _seeded()
    cache_b, trie_b, _, _ = _seeded()
    peer = _FakePeer()
    prop = PrefixPropagator(trie_a, [peer], rpc_timeout=1.0)
    trie_a.attach_propagator(prop)
    try:
        assert trie_a.insert(np.array([1, 2, 3, 4], np.int32), slot_a)
        deadline = time.monotonic() + 10.0
        while not peer.transport.posts and time.monotonic() < deadline:
            time.sleep(0.01)
        assert peer.transport.posts, "propagator never posted"
        path, body, kw = peer.transport.posts[0]
        assert path == "/replica/prefix" and kw.get("replay_safe") is True
        assert body["tokens"] == [1, 2, 3, 4]
        assert body["hash"] == PrefixTrie.token_hash((1, 2, 3, 4))
        assert prop.sent == 1 and prop.errors == 0

        # receiver side: decode + adopt, byte-identical state
        state = decode_propagated_state(
            body, num_layers=2, hidden_size=4)
        assert state is not None
        np.testing.assert_array_equal(state.h, h0)
        np.testing.assert_array_equal(state.c, c0)
        assert trie_b.adopt_remote(body["tokens"], state,
                                   body["hash"]) == "applied"
        node, n = trie_b.lookup(np.array([1, 2, 3, 4, 9], np.int32))
        assert node is not None and n == 4
        np.testing.assert_array_equal(
            np.asarray(cache_b.h[:, node.slot, :]), h0)
        trie_b.release(node)

        # idempotency leg 1: token path already stateful -> dedup
        assert trie_b.adopt_remote(body["tokens"], state,
                                   body["hash"]) == "dedup"
        # idempotency leg 2: node evicted but hash recently applied ->
        # an at-least-once replay still dedups instead of resurrecting
        trie_b.clear()
        assert len(trie_b) == 0
        assert trie_b.adopt_remote(body["tokens"], state,
                                   body["hash"]) == "dedup"
        st = trie_b.stats()
        assert st["propagated_in"] == 1 and st["propagation_dedup"] == 2

        # rejection: off-stride length, wrong state shape
        assert trie_b.adopt_remote([1, 2, 3], state, None) == "rejected"
        bad = DetachedState(h=np.zeros((3, 4), np.float32),
                            c=np.zeros((3, 4), np.float32))
        assert trie_b.adopt_remote([5, 6], bad, None) == "rejected"

        # circuit-suspect peers are skipped, not queued behind
        peer.suspected = True
        before = len(peer.transport.posts)
        prop._send((7, 8), DetachedState(h=h0, c=c0))
        assert len(peer.transport.posts) == before and prop.sent == 1
    finally:
        prop.close()


def test_decode_propagated_state_rejects_malformed():
    cache, trie, slot, (h0, c0) = _seeded()
    assert trie.insert(np.array([1, 2], np.int32), slot)
    prop = PrefixPropagator(trie, [])
    body = None
    # build a valid body through the real serializer path
    peer = _FakePeer()
    prop.peers = [peer]
    prop._send((1, 2), DetachedState(h=h0, c=c0))
    _, body, _ = peer.transport.posts[0]
    assert decode_propagated_state(
        body, num_layers=2, hidden_size=4) is not None
    # wrong geometry
    assert decode_propagated_state(
        body, num_layers=3, hidden_size=4) is None
    # tampered tokens no longer match the hash (integrity check)
    bad = dict(body, tokens=[9, 9])
    assert decode_propagated_state(bad, num_layers=2, hidden_size=4) is None
    # truncated payload
    bad = dict(body, h=body["h"][:8])
    assert decode_propagated_state(bad, num_layers=2, hidden_size=4) is None
    # missing field
    bad = {k: v for k, v in body.items() if k != "c"}
    assert decode_propagated_state(bad, num_layers=2, hidden_size=4) is None
    prop.close()


def test_remote_engine_forwards_peer_prefix_section():
    """ISSUE-19 satellite: _RemoteEngine.stats() must mirror the peer's
    real prefix-cache section off the heartbeat, not hardcode None."""
    from lstm_tensorspark_tpu.serve.remote import RemoteBatcher, _RemoteEngine
    from lstm_tensorspark_tpu.obs import MetricsRegistry

    shim = RemoteBatcher("http://127.0.0.1:9", replica=0,
                         registry=MetricsRegistry())
    eng = _RemoteEngine(shim, None)
    assert eng.stats()["prefix_cache"] is None   # no heartbeat yet
    with shim._lock:
        shim._remote_prefix = {"mode": "trie", "hits": 3}
    assert eng.stats()["prefix_cache"] == {"mode": "trie", "hits": 3}
    assert shim.remote_prefix() == {"mode": "trie", "hits": 3}


def test_server_replica_prefix_route_applies_and_dedups():
    """POST /replica/prefix on a fabric server lands the node in the
    local trie (applied), replays dedup, malformed bodies reject."""
    from lstm_tensorspark_tpu.serve.server import make_http_server

    _, engine = _make_engine(prefix_fabric=True)
    server = ServeServer(engine, max_active=2, queue_size=4)
    httpd = make_http_server(server, port=0)
    host, port = httpd.server_address[:2]
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)

    h = np.arange(_CFG.num_layers * _CFG.hidden_size,
                  dtype=np.float32).reshape(_CFG.num_layers,
                                            _CFG.hidden_size)
    toks = list(range(8))   # stride multiple (engine default stride 8)
    import base64 as _b64
    body = {
        "tokens": toks,
        "hash": PrefixTrie.token_hash(tuple(toks)),
        "layers": _CFG.num_layers,
        "hidden": _CFG.hidden_size,
        "h": _b64.b64encode(h.tobytes()).decode("ascii"),
        "c": _b64.b64encode((-h).tobytes()).decode("ascii"),
    }

    def _post(payload):
        req = urllib.request.Request(
            f"http://{host}:{port}/replica/prefix",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    try:
        with server:
            thread.start()
            status, out = _post(body)
            assert status == 200 and out["applied"] == 1
            status, out = _post(body)
            assert status == 200 and out["dedup"] == 1
            status, _ = _post(dict(body, tokens=toks[:3]))   # off-stride
            assert status == 400
            node, n = engine.prefix.lookup(
                np.asarray(toks + [1], np.int32))
            assert node is not None and n == 8
            engine.prefix.release(node)
            hb = server.replica_heartbeat()
            px = hb["prefix_cache"]
            assert px is not None and px["mode"] == "trie"
            assert px["propagated_in"] == 1
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---- greedy parity through the full stack -------------------------------


def test_parity_fabric_cold_hot_chunked_and_pallas():
    """Greedy output is token-identical across {fabric off, fabric on,
    fabric on + chunked prefill, fabric on + Pallas decode kernel},
    cold and hot, all matching models/generate.py — and the hot passes
    genuinely resume from trie nodes."""
    rng = np.random.RandomState(3)
    shared = rng.randint(0, 37, size=8).astype(np.int32)
    prompts = [
        np.concatenate([shared, rng.randint(0, 37, size=5).astype(np.int32)])
        for _ in range(3)
    ]
    n_new = 6
    refs = None
    for kw_e, kw_b in [
        ({}, {}),
        ({"prefix_fabric": True}, {}),
        ({"prefix_fabric": True}, {"prefill_chunk": 4}),
        ({"prefix_fabric": True, "decode_kernel": "pallas"}, {}),
    ]:
        params, engine = _make_engine(**kw_e)
        if refs is None:
            refs = _refs(params, prompts, n_new)
        batcher = Batcher(engine, max_active=4, queue_size=8, **kw_b)
        assert _run(batcher, prompts, n_new) == refs   # cold
        assert _run(batcher, prompts, n_new) == refs   # hot
        if engine.prefix is not None:
            st = engine.prefix.stats()
            assert st["mode"] == "trie"
            assert st["hits"] >= 3, st
            assert st["inserts"] >= 1, st
            assert batcher.prefix_tokens_saved >= 8 * 3
            assert batcher.prefill_tokens_computed > 0


def test_fabric_resume_zero_mid_traffic_compiles():
    """A trie-resumed hot pass reuses only warmed programs: the compile
    counters must not move after the cold pass."""
    rng = np.random.RandomState(9)
    shared = rng.randint(0, 37, size=8).astype(np.int32)
    cold = np.concatenate([shared,
                           rng.randint(0, 37, size=5).astype(np.int32)])
    hot = np.concatenate([shared,
                          rng.randint(0, 37, size=5).astype(np.int32)])
    params, engine = _make_engine(prefix_fabric=True)
    refs = _refs(params, [cold, hot], 4)
    batcher = Batcher(engine, max_active=4, queue_size=8)
    assert _run(batcher, [cold], 4) == refs[:1]
    before = dict(engine.compile_counts)
    assert _run(batcher, [hot], 4) == refs[1:]
    assert dict(engine.compile_counts) == before
    assert engine.prefix.stats()["hits"] >= 1
