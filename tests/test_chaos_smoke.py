"""The one-command chaos drill (tools/chaos_smoke.py), wired as a `-m slow`
test: runnable on demand, off the tier-1 hot path (it launches several
full CLI subprocesses)."""

import os
import sys

import pytest

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


@pytest.mark.slow
def test_chaos_smoke_drill(tmp_path):
    import chaos_smoke

    rc = chaos_smoke.main(["--steps", "12", "--keep", str(tmp_path / "work")])
    assert rc == 0
