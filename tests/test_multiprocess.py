"""Multi-host control plane (parallel/mesh.py distributed_init): a REAL
2-process jax.distributed run over CPU+Gloo — the strongest available
validation of the multi-host story without pod hardware (SURVEY.md §7
step 4). Each process owns 2 virtual devices of a 4-device global mesh;
the DP train step's pmean crosses the process boundary; the resulting
loss and updated params must match the single-process full-batch program.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

pid = int(sys.argv[1])
port = sys.argv[2]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_dp_train_step
from lstm_tensorspark_tpu.train import make_optimizer
from lstm_tensorspark_tpu.train.loop import init_train_state

B, T, V, H = 8, 12, 23, 16
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
def loss_fn(p, b, r): return lm_loss(p, b, cfg)
opt = make_optimizer("sgd", 0.5)
params = init_lm(jax.random.PRNGKey(0), cfg)

mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))

rng = np.random.RandomState(0)
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}

def put(tree, spec):
    def one(a):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: np.asarray(a)[idx]
        )
    return jax.tree.map(one, tree)

state = init_train_state(params, opt, jax.random.PRNGKey(1))
state = state._replace(
    params=put(jax.device_get(state.params), P()),
    opt_state=put(jax.device_get(state.opt_state), P()),
    step=put(np.asarray(state.step), P()),
    rng=put(np.asarray(state.rng), P()),
)
batch = put(batch_host, P("data"))

step = make_dp_train_step(loss_fn, opt, mesh)
state, m = step(state, batch)
state, m = step(state, batch)
loss = float(m["loss"])

# single-process oracle: the same two full-batch steps, no mesh
from lstm_tensorspark_tpu.train import make_train_step
s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
ref_step = make_train_step(loss_fn, opt)
s2, m2 = ref_step(s2, batch_host)
s2, m2 = ref_step(s2, batch_host)
ref = float(m2["loss"])
assert abs(loss - ref) < 1e-5, (loss, ref)
print(f"proc {pid}: dp-2proc loss={loss:.6f} matches single={ref:.6f}", flush=True)
'''


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_dp_training_parity(tmp_path):
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:  # never leave orphans holding the coordinator port
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "matches single" in out
