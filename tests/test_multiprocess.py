"""Multi-host control plane (parallel/mesh.py distributed_init): a REAL
2-process jax.distributed run over CPU+Gloo — the strongest available
validation of the multi-host story without pod hardware (SURVEY.md §7
step 4). Each process owns 2 virtual devices of a 4-device global mesh;
the DP train step's pmean crosses the process boundary; the resulting
loss and updated params must match the single-process full-batch program.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

pid = int(sys.argv[1])
port = sys.argv[2]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_dp_train_step
from lstm_tensorspark_tpu.train import make_optimizer
from lstm_tensorspark_tpu.train.loop import init_train_state

B, T, V, H = 8, 12, 23, 16
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
def loss_fn(p, b, r): return lm_loss(p, b, cfg)
opt = make_optimizer("sgd", 0.5)
params = init_lm(jax.random.PRNGKey(0), cfg)

mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))

rng = np.random.RandomState(0)
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}

def put(tree, spec):
    def one(a):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: np.asarray(a)[idx]
        )
    return jax.tree.map(one, tree)

state = init_train_state(params, opt, jax.random.PRNGKey(1))
state = state._replace(
    params=put(jax.device_get(state.params), P()),
    opt_state=put(jax.device_get(state.opt_state), P()),
    step=put(np.asarray(state.step), P()),
    rng=put(np.asarray(state.rng), P()),
)
batch = put(batch_host, P("data"))

step = make_dp_train_step(loss_fn, opt, mesh)
state, m = step(state, batch)
state, m = step(state, batch)
loss = float(m["loss"])

# single-process oracle: the same two full-batch steps, no mesh
from lstm_tensorspark_tpu.train import make_train_step
s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
ref_step = make_train_step(loss_fn, opt)
s2, m2 = ref_step(s2, batch_host)
s2, m2 = ref_step(s2, batch_host)
ref = float(m2["loss"])
assert abs(loss - ref) < 1e-5, (loss, ref)
print(f"proc {pid}: dp-2proc loss={loss:.6f} matches single={ref:.6f}", flush=True)
'''


_CKPT_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); port = sys.argv[2]; ckpt_dir = sys.argv[3]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import numpy as np
from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.parallel import (
    make_mesh, make_pp_lm_train_step, place_pp_lm_params, stack_lm_params,
)
from lstm_tensorspark_tpu.train import make_optimizer
from lstm_tensorspark_tpu.train.checkpoint import Checkpointer
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 13, 16, 8, 12
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
opt = make_optimizer("adam", 1e-2)  # adam: momenta are PP-sharded too
mesh = make_mesh(dp=2, pp=2)  # 4 global devices, 2 per process

stacked = stack_lm_params(init_lm(jax.random.PRNGKey(0), cfg))
placed = place_pp_lm_params(stacked, mesh)
step = make_pp_lm_train_step(cfg, opt, mesh, stacked, microbatches=2,
                             donate=False)
state = init_train_state(placed, opt, jax.random.PRNGKey(1))

rng = np.random.RandomState(0)
from jax.sharding import NamedSharding, PartitionSpec as P
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}
batch = jax.tree.map(
    lambda a: jax.make_array_from_callback(
        a.shape, NamedSharding(mesh, P("data")), lambda idx: a[idx]
    ),
    batch_host,
)
state, m = step(state, batch)   # step 1: PP-sharded params + adam moments

ck = Checkpointer(ckpt_dir)
ck.save(state)                  # per-process shard files + marker

# fresh template with DIFFERENT values, same structure/shardings
stacked2 = stack_lm_params(init_lm(jax.random.PRNGKey(7), cfg))
template = init_train_state(place_pp_lm_params(stacked2, mesh), opt,
                            jax.random.PRNGKey(8))
restored = ck.restore_latest(template)
assert restored is not None
assert int(jax.device_get(restored.step)) == 1

# every local shard must round-trip exactly (scalar leaves like the adam
# step count restore as host numpy — compare values directly)
def check(a, b):
    if hasattr(a, "addressable_shards") and hasattr(b, "addressable_shards"):
        for sa, sb in zip(a.addressable_shards, b.addressable_shards):
            np.testing.assert_array_equal(np.asarray(sa.data),
                                          np.asarray(sb.data))
    else:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )
jax.tree.map(check, state.params, restored.params)
jax.tree.map(check, state.opt_state, restored.opt_state)

# and the restored state must be trainable (chains into the step)
restored2, m2 = step(restored, batch)
state2, m_want = step(state, batch)
assert abs(float(m2["loss"]) - float(m_want["loss"])) < 1e-6
print(f"proc {pid}: sharded checkpoint round-trip ok", flush=True)
'''


_DEVDATA_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

pid = int(sys.argv[1]); port = sys.argv[2]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import numpy as np
from jax.sharding import Mesh

from lstm_tensorspark_tpu.data import stage_lm_data
from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.train import (
    make_device_dp_lm_train_step,
    make_device_lm_train_step,
    make_optimizer,
)
from lstm_tensorspark_tpu.train.loop import init_train_state

B, T, V, H, K = 8, 12, 23, 16, 2
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
def loss_fn(p, b, r): return lm_loss(p, b, cfg)
opt = make_optimizer("sgd", 0.5)
params = init_lm(jax.random.PRNGKey(0), cfg)

mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))

rng = np.random.RandomState(0)
train_tokens = rng.randint(0, V, B * T * 6 + 1).astype(np.int32)
valid_tokens = rng.randint(0, V, B * T * 2 + 1).astype(np.int32)

# device-resident staging onto the GLOBAL mesh: each process materialises
# only its addressable row shards (every process holds the full host array)
ddata = stage_lm_data(train_tokens, B, T, mesh=mesh)
edata = stage_lm_data(valid_tokens, B, T, mesh=mesh)

from lstm_tensorspark_tpu.parallel.data_parallel import replicate
state = init_train_state(params, opt, jax.random.PRNGKey(1))
state = state._replace(
    params=replicate(state.params, mesh),
    opt_state=replicate(state.opt_state, mesh),
    step=replicate(np.asarray(state.step), mesh),
    rng=replicate(np.asarray(state.rng), mesh),
)

dstep = make_device_dp_lm_train_step(
    loss_fn, opt, ddata, mesh, eval_data=edata, steps_per_call=K,
    donate=False,
)
state, m = dstep(state, ddata.arrays, np.int32(0), edata.arrays,
                 np.bool_(True), None)
loss, ev = float(m["loss"]), float(m["eval_loss"])

# single-device oracle in the same process: full batch, local arrays
ddata_l = stage_lm_data(train_tokens, B, T)
edata_l = stage_lm_data(valid_tokens, B, T)
sstep = make_device_lm_train_step(
    loss_fn, opt, ddata_l, eval_data=edata_l, steps_per_call=K, donate=False,
)
s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
s2, m2 = sstep(s2, ddata_l.arrays, np.int32(0), edata_l.arrays,
               np.bool_(True))
ref, ref_ev = float(m2["loss"]), float(m2["eval_loss"])
assert abs(loss - ref) < 1e-5, (loss, ref)
assert abs(ev - ref_ev) < 1e-5, (ev, ref_ev)
print(f"proc {pid}: devdata+fused 2proc loss={loss:.6f} eval={ev:.6f} "
      f"match single ({ref:.6f}, {ref_ev:.6f})", flush=True)
'''


_HYBRID_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

pid = int(sys.argv[1]); port = sys.argv[2]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_dp_train_step, make_hybrid_mesh
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

# Placement law: slice-major order ⇒ data shard i is EXACTLY process i's
# devices (2 procs x 2 local devices, dp=2, tp=2 — the tp block fills one
# process, so tp's per-timestep collectives never cross Gloo/DCN).
mesh_tp = make_hybrid_mesh(dp=2, tp=2)
for shard in range(2):
    procs = {d.process_index for d in mesh_tp.devices[shard].flat}
    assert procs == {shard}, (shard, procs)

# Training parity through the SAME entry the CLI uses: DP over the hybrid
# mesh must reproduce the single-process full-batch program bit-for-bit
# (one domain per process here, so the data pmean crosses Gloo).
B, T, V, H = 8, 12, 23, 16
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
def loss_fn(p, b, r): return lm_loss(p, b, cfg)
opt = make_optimizer("sgd", 0.5)
params = init_lm(jax.random.PRNGKey(0), cfg)
mesh = make_hybrid_mesh(dp=4)

rng = np.random.RandomState(0)
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}

def put(tree, spec):
    def one(a):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: np.asarray(a)[idx]
        )
    return jax.tree.map(one, tree)

state = init_train_state(params, opt, jax.random.PRNGKey(1))
state = state._replace(
    params=put(jax.device_get(state.params), P()),
    opt_state=put(jax.device_get(state.opt_state), P()),
    step=put(np.asarray(state.step), P()),
    rng=put(np.asarray(state.rng), P()),
)
batch = put(batch_host, P("data"))

step = make_dp_train_step(loss_fn, opt, mesh)
state, m = step(state, batch)
state, m = step(state, batch)
loss = float(m["loss"])

s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
ref_step = make_train_step(loss_fn, opt)
s2, m2 = ref_step(s2, batch_host)
s2, m2 = ref_step(s2, batch_host)
ref = float(m2["loss"])
assert abs(loss - ref) < 1e-5, (loss, ref)
print(f"proc {pid}: hybrid mesh placement + parity ok "
      f"loss={loss:.6f} ref={ref:.6f}", flush=True)
'''


_HYBRID4_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

pid = int(sys.argv[1]); port = sys.argv[2]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 4, pid)
assert jax.process_count() == 4

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_dp_train_step, make_hybrid_mesh
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

# Placement law at FOUR domains (VERDICT r4 #10 — the slice-major device
# order was previously only exercised at 2 processes): dp=4 x tp=2 over
# 4 procs x 2 local devices, so each data shard — and each whole tp
# block — is EXACTLY one process's devices; tp's per-timestep collectives
# never touch Gloo/DCN.
mesh_tp = make_hybrid_mesh(dp=4, tp=2)
for shard in range(4):
    procs = {d.process_index for d in mesh_tp.devices[shard].flat}
    assert procs == {shard}, (shard, procs)

# DP training parity over dp=8 (2 local devices x 4 domains): the data
# psum's topology decomposes into an intra-process phase plus one
# 4-process Gloo phase, and must still reproduce the single-process
# full-batch program.
B, T, V, H = 8, 12, 23, 16
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
def loss_fn(p, b, r): return lm_loss(p, b, cfg)
opt = make_optimizer("sgd", 0.5)
params = init_lm(jax.random.PRNGKey(0), cfg)
mesh = make_hybrid_mesh(dp=8)

rng = np.random.RandomState(0)
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}

def put(tree, spec):
    def one(a):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: np.asarray(a)[idx]
        )
    return jax.tree.map(one, tree)

state = init_train_state(params, opt, jax.random.PRNGKey(1))
state = state._replace(
    params=put(jax.device_get(state.params), P()),
    opt_state=put(jax.device_get(state.opt_state), P()),
    step=put(np.asarray(state.step), P()),
    rng=put(np.asarray(state.rng), P()),
)
batch = put(batch_host, P("data"))

step = make_dp_train_step(loss_fn, opt, mesh)
state, m = step(state, batch)
state, m = step(state, batch)
loss = float(m["loss"])

s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
ref_step = make_train_step(loss_fn, opt)
s2, m2 = ref_step(s2, batch_host)
s2, m2 = ref_step(s2, batch_host)
ref = float(m2["loss"])
assert abs(loss - ref) < 1e-5, (loss, ref)
print(f"proc {pid}: hybrid-4proc placement + parity ok "
      f"loss={loss:.6f} ref={ref:.6f}", flush=True)
'''


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_procs(worker: str, *extra_argv: str, expect: str,
               n: int = 2) -> None:
    """THE n-process harness shared by every multiprocess test: spawn all
    ranks (rank id + coordinator port + extra argv), bound their runtime,
    never leave orphans holding the coordinator port, and assert all exit
    cleanly with ``expect`` in their output."""
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker, str(i), port, *extra_argv],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env,
        )
        for i in range(n)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert expect in out


def _run_two_procs(worker: str, *extra_argv: str, expect: str) -> None:
    _run_procs(worker, *extra_argv, expect=expect, n=2)


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_dp_training_parity():
    _run_two_procs(_WORKER, expect="matches single")


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_device_data_fused_eval_parity():
    """Device-resident data + fused in-executable eval across a REAL process
    boundary: HBM staging materialises only each process's addressable row
    shards; the fused eval's token-weighted psum crosses Gloo; training
    loss AND eval loss must match the single-device full-batch program."""
    _run_two_procs(_DEVDATA_WORKER, expect="match single")


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_pp_sharded_checkpoint(tmp_path):
    """Multi-host-safe checkpointing (VERDICT r1 weak #6): 2 real processes,
    PP-sharded params + adam moments; per-process shard files, marker-gated
    restorability, reshard-on-restore, and trainability of the result."""
    ckpt = str(tmp_path / "ckpt")
    _run_two_procs(_CKPT_WORKER, ckpt, expect="round-trip ok")
    # both processes wrote their own shard file; step marked complete
    names = os.listdir(ckpt)
    assert "step_1.complete" in names
    # payload count only: each shard file also carries a .sha256 sidecar
    assert sum(1 for n in names if n.startswith("step_1.proc")
               and n.endswith(".msgpack")) == 2


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_hybrid_mesh_placement_and_parity():
    """DCN-aware hybrid mesh over a REAL process boundary: slice-major
    ordering puts each data shard (and each whole tp block) inside one
    process's devices, and DP training over the hybrid mesh matches the
    single-process full-batch program."""
    _run_two_procs(_HYBRID_WORKER, expect="hybrid mesh placement + parity ok")


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_four_process_hybrid_mesh_placement_and_parity():
    """VERDICT r4 #10: the slice-major placement law at FOUR interconnect
    domains — each dp=4 x tp=2 block inside one process, and dp=8 DP
    training (intra-process + 4-way Gloo psum phases) matching the
    single-process full-batch program."""
    _run_procs(_HYBRID4_WORKER, expect="hybrid-4proc placement + parity ok",
               n=4)


_ZERO1_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

pid = int(sys.argv[1]); port = sys.argv[2]
# optional 3rd arg: process count (default 2) — the 4-process case runs
# the SAME program with the scatter/gather decomposing over 4 Gloo peers
nprocs = int(sys.argv[3]) if len(sys.argv) > 3 else 2

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", nprocs, pid)
assert jax.process_count() == nprocs

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_hybrid_mesh
from lstm_tensorspark_tpu.parallel.zero import (
    make_zero1_opt_init, make_zero1_train_step,
)
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

B, T, V, H = 8, 12, 23, 16
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
def loss_fn(p, b, r): return lm_loss(p, b, cfg)
opt = make_optimizer("adam", 1e-2)
params = init_lm(jax.random.PRNGKey(0), cfg)
mesh = make_hybrid_mesh(dp=2 * nprocs)

rng = np.random.RandomState(0)
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}

def put(tree, spec):
    def one(a):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: np.asarray(a)[idx]
        )
    return jax.tree.map(one, tree)

state = init_train_state(params, opt, jax.random.PRNGKey(1))
state = state._replace(
    params=put(jax.device_get(state.params), P()),
    opt_state=make_zero1_opt_init(opt, mesh)(
        put(jax.device_get(state.params), P())),
    step=put(np.asarray(state.step), P()),
    rng=put(np.asarray(state.rng), P()),
)
batch = put(batch_host, P("data"))

# reduce-scatter + sliced adam update + all-gather, with the scatter and
# gather both CROSSING the real Gloo process boundary
step = make_zero1_train_step(loss_fn, opt, mesh, donate=False)
state, m = step(state, batch)
state, m = step(state, batch)
loss = float(m["loss"])

# single-process oracle: plain full-batch adam, no mesh
s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
ref_step = make_train_step(loss_fn, opt)
s2, m2 = ref_step(s2, batch_host)
s2, m2 = ref_step(s2, batch_host)
ref = float(m2["loss"])
assert abs(loss - ref) < 1e-5, (loss, ref)

# and the updated params must match too (the all-gather rebuilt them from
# slices updated on DIFFERENT processes)
for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                jax.tree.leaves(jax.device_get(s2.params))):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=1e-6)
print(f"proc {pid}: zero1-{nprocs}proc loss={loss:.6f} "
      f"matches single={ref:.6f}", flush=True)
'''


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_zero1_training_parity():
    """ZeRO-1 across a REAL process boundary: the gradient reduce-scatter
    and the parameter all-gather both cross Gloo; each process updates
    disjoint slices of the raveled params with its own adam-moment shards,
    and the result must match the single-process full-batch program."""
    _run_two_procs(_ZERO1_WORKER, expect="zero1-2proc loss")


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_four_process_zero1_training_parity():
    """ZeRO-1 at FOUR Gloo domains (dp=8 over 4 procs x 2 devices): the
    gradient reduce-scatter and parameter all-gather decompose over four
    process boundaries; each process updates disjoint slices of the
    raveled params, and loss AND rebuilt params must still match the
    single-process full-batch program."""
    _run_procs(_ZERO1_WORKER, "4", expect="zero1-4proc loss", n=4)


_ZERO1_TP_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

pid = int(sys.argv[1]); port = sys.argv[2]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import GetAttrKey, tree_flatten_with_path

from lstm_tensorspark_tpu.models import (
    ClassifierConfig, classifier_loss, init_classifier,
)
from lstm_tensorspark_tpu.parallel import make_hybrid_mesh
from lstm_tensorspark_tpu.parallel.tensor_parallel import (
    classifier_param_specs, make_tp_train_step,
)
from lstm_tensorspark_tpu.parallel.zero import zero1_tp_opt_specs
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

B, T, V, H = 8, 12, 23, 16
cfg = ClassifierConfig(vocab_size=V, hidden_size=H, num_layers=1)
def loss_fn(p, b, r): return classifier_loss(p, b, cfg)
opt = make_optimizer("adam", 1e-2)
params = init_classifier(jax.random.PRNGKey(0), cfg)
# slice-major hybrid mesh: each tp block lives inside ONE process, the
# data axis crosses the (Gloo) process boundary
mesh = make_hybrid_mesh(dp=2, tp=2)
specs = classifier_param_specs(params)
opt_specs = zero1_tp_opt_specs(opt, params, specs, mesh)

rng = np.random.RandomState(0)
batch_host = {
    "tokens": rng.randint(0, V, (B, T)).astype(np.int32),
    "lengths": np.full((B,), T, np.int32),
    "labels": rng.randint(0, 2, (B,)).astype(np.int32),
    "valid": np.ones((B,), np.float32),
}

def put_leaf(a, spec):
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        a.shape, sharding, lambda idx: np.asarray(a)[idx]
    )

def put_tree(tree, spec_tree):
    return jax.tree.map(
        put_leaf, jax.device_get(tree), spec_tree,
        is_leaf=lambda x: isinstance(x, (jax.Array, np.ndarray)),
    )

def put(tree, spec):
    return jax.tree.map(lambda a: put_leaf(np.asarray(a), spec),
                        jax.device_get(tree))

state = init_train_state(params, opt, jax.random.PRNGKey(1))
state = state._replace(
    params=put_tree(state.params, specs),
    opt_state=put_tree(state.opt_state, opt_specs),
    step=put(np.asarray(state.step), P()),
    rng=put(np.asarray(state.rng), P()),
)
# every batch leaf is batch-major: shard dim0 over data
batch = {k: put_leaf(np.asarray(v), P("data")) for k, v in batch_host.items()}

step = make_tp_train_step(loss_fn, opt, mesh, params, param_specs=specs,
                          opt_state_specs=opt_specs, donate=False)
state, m = step(state, batch)
state, m = step(state, batch)
loss = float(m["loss"])

# the data-sharded moments live on devices of BOTH processes
leaves = tree_flatten_with_path(state.opt_state)[0]
mats = [a for path, a in leaves if GetAttrKey("mu") in path and a.ndim == 2]
assert any("data" in a.sharding.spec and "model" in a.sharding.spec
           for a in mats), [a.sharding.spec for a in mats]

# single-process oracle: plain full-batch adam, no mesh
s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
ref_step = make_train_step(loss_fn, opt)
s2, m2 = ref_step(s2, batch_host)
s2, m2 = ref_step(s2, batch_host)
ref = float(m2["loss"])
assert abs(loss - ref) < 1e-5, (loss, ref)
for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                jax.tree.leaves(jax.device_get(s2.params))):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=1e-6)
print(f"proc {pid}: zero1-tp-2proc loss={loss:.6f} matches single={ref:.6f}",
      flush=True)
'''


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_zero1_tp_training_parity():
    """GSPMD ZeRO-1 x TP across a REAL process boundary: tp blocks stay
    inside one process (slice-major hybrid mesh), the data axis — and the
    moments sharded over it — spans both; trajectory and final params must
    match the single-process full-batch program."""
    _run_two_procs(_ZERO1_TP_WORKER, expect="matches single")


_BEST_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); port = sys.argv[2]; ckpt_dir = sys.argv[3]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import numpy as np
from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.parallel import (
    make_mesh, make_pp_lm_train_step, place_pp_lm_params, stack_lm_params,
)
from lstm_tensorspark_tpu.train import make_optimizer
from lstm_tensorspark_tpu.train.checkpoint import Checkpointer
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 13, 16, 8, 12
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
opt = make_optimizer("adam", 1e-2)  # adam: momenta are PP-sharded too
mesh = make_mesh(dp=2, pp=2)  # 4 global devices, 2 per process

stacked = stack_lm_params(init_lm(jax.random.PRNGKey(0), cfg))
placed = place_pp_lm_params(stacked, mesh)
step = make_pp_lm_train_step(cfg, opt, mesh, stacked, microbatches=2,
                             donate=False)
state = init_train_state(placed, opt, jax.random.PRNGKey(1))

rng = np.random.RandomState(0)
from jax.sharding import NamedSharding, PartitionSpec as P
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}
batch = jax.tree.map(
    lambda a: jax.make_array_from_callback(
        a.shape, NamedSharding(mesh, P("data")), lambda idx: a[idx]
    ),
    batch_host,
)

state, m = step(state, batch)        # step 1
ck = Checkpointer(ckpt_dir)
ck.save_best(state, 1.25)            # first best
state2, _ = step(state, batch)       # step 2
ck.save_best(state2, 0.5)            # improvement: marker + files move
ck.save(state2)                      # step checkpoint of the SAME state
meta = ck.best_meta()
assert meta == {"step": 2, "value": 0.5}, meta

# exactly one live shard set remains after the overwrite (pid 0 looks
# after save_best's final barrier)
if pid == 0:
    files = sorted(n for n in os.listdir(ckpt_dir) if n.startswith("best_")
                   and n.endswith(".msgpack"))
    assert files == ["best_2.proc0.msgpack", "best_2.proc1.msgpack"], files

# fresh-template restore: every local shard round-trips exactly
stacked2 = stack_lm_params(init_lm(jax.random.PRNGKey(7), cfg))
template = init_train_state(place_pp_lm_params(stacked2, mesh), opt,
                            jax.random.PRNGKey(8))
restored = ck.restore_best(template)
assert restored is not None
assert int(jax.device_get(restored.step)) == 2

def check(a, b):
    if hasattr(a, "addressable_shards") and hasattr(b, "addressable_shards"):
        for sa, sb in zip(a.addressable_shards, b.addressable_shards):
            np.testing.assert_array_equal(np.asarray(sa.data),
                                          np.asarray(sb.data))
    else:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )
jax.tree.map(check, state2.params, restored.params)
jax.tree.map(check, state2.opt_state, restored.opt_state)

# and it chains into training
restored3, m3 = step(restored, batch)
state3, want = step(state2, batch)
assert abs(float(m3["loss"]) - float(want["loss"])) < 1e-6

# --resume-best REWIND flow, multi-process (r4): train past the best,
# checkpoint the diverged lineage, then rewind — fence the newer steps
# (pid-0 deletes behind barriers) and re-save the rewound point; a later
# restore_latest must land on the best, not the abandoned lineage.
state3, _ = step(state2, batch)      # step 3 (diverged lineage)
ck.save(state3)
assert ck.latest_step() == 3
rewound = ck.restore_best(template)
ck.fence_after(int(jax.device_get(rewound.step)))
ck.save(rewound)
assert ck.latest_step() == 2
relatest = ck.restore_latest(template)
jax.tree.map(check, rewound.params, relatest.params)
print(f"proc {pid}: sharded best checkpoint ok", flush=True)
'''


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_keep_best_sharded(tmp_path):
    """Multi-process --keep-best (VERDICT r3 item 7): save_best routes
    through the sharded writer — per-process best_<step>.proc<k> files +
    a best.complete marker — overwrite moves the marker atomically, and
    restore_best reassembles the shards."""
    ckpt = str(tmp_path / "ck")
    _run_two_procs(_BEST_WORKER, ckpt, expect="sharded best checkpoint ok")

    # cross-process-count restore: THIS process (1 process, its own mesh
    # with a DIFFERENT dp) restores the 2-process best AND the 2-process
    # step checkpoint of the same state — they must agree leaf for leaf.
    import jax

    from lstm_tensorspark_tpu.models import LMConfig, init_lm
    from lstm_tensorspark_tpu.parallel import (
        make_mesh, place_pp_lm_params, stack_lm_params,
    )
    from lstm_tensorspark_tpu.train import make_optimizer
    from lstm_tensorspark_tpu.train.checkpoint import Checkpointer
    from lstm_tensorspark_tpu.train.loop import init_train_state

    cfg = LMConfig(vocab_size=13, hidden_size=16, num_layers=2)
    opt = make_optimizer("adam", 1e-2)
    mesh = make_mesh(dp=4, pp=2)  # writer used dp=2,pp=2 over 2 processes
    stacked = stack_lm_params(init_lm(jax.random.PRNGKey(7), cfg))
    template = init_train_state(
        place_pp_lm_params(stacked, mesh), opt, jax.random.PRNGKey(8))
    ck = Checkpointer(ckpt)
    assert ck.best_meta() == {"step": 2, "value": 0.5}
    best = ck.restore_best(template)
    latest = ck.restore_latest(template)
    assert best is not None and latest is not None
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        best.params, latest.params,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))),
        best.opt_state, latest.opt_state,
    )
