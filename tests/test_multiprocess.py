"""Multi-host control plane (parallel/mesh.py distributed_init): a REAL
2-process jax.distributed run over CPU+Gloo — the strongest available
validation of the multi-host story without pod hardware (SURVEY.md §7
step 4). Each process owns 2 virtual devices of a 4-device global mesh;
the DP train step's pmean crosses the process boundary; the resulting
loss and updated params must match the single-process full-batch program.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

pid = int(sys.argv[1])
port = sys.argv[2]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_dp_train_step
from lstm_tensorspark_tpu.train import make_optimizer
from lstm_tensorspark_tpu.train.loop import init_train_state

B, T, V, H = 8, 12, 23, 16
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
def loss_fn(p, b, r): return lm_loss(p, b, cfg)
opt = make_optimizer("sgd", 0.5)
params = init_lm(jax.random.PRNGKey(0), cfg)

mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))

rng = np.random.RandomState(0)
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}

def put(tree, spec):
    def one(a):
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            a.shape, sharding, lambda idx: np.asarray(a)[idx]
        )
    return jax.tree.map(one, tree)

state = init_train_state(params, opt, jax.random.PRNGKey(1))
state = state._replace(
    params=put(jax.device_get(state.params), P()),
    opt_state=put(jax.device_get(state.opt_state), P()),
    step=put(np.asarray(state.step), P()),
    rng=put(np.asarray(state.rng), P()),
)
batch = put(batch_host, P("data"))

step = make_dp_train_step(loss_fn, opt, mesh)
state, m = step(state, batch)
state, m = step(state, batch)
loss = float(m["loss"])

# single-process oracle: the same two full-batch steps, no mesh
from lstm_tensorspark_tpu.train import make_train_step
s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
ref_step = make_train_step(loss_fn, opt)
s2, m2 = ref_step(s2, batch_host)
s2, m2 = ref_step(s2, batch_host)
ref = float(m2["loss"])
assert abs(loss - ref) < 1e-5, (loss, ref)
print(f"proc {pid}: dp-2proc loss={loss:.6f} matches single={ref:.6f}", flush=True)
'''


_CKPT_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); port = sys.argv[2]; ckpt_dir = sys.argv[3]

from lstm_tensorspark_tpu.parallel import distributed_init
distributed_init(f"127.0.0.1:{port}", 2, pid)
assert jax.process_count() == 2

import numpy as np
from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.parallel import (
    make_mesh, make_pp_lm_train_step, place_pp_lm_params, stack_lm_params,
)
from lstm_tensorspark_tpu.train import make_optimizer
from lstm_tensorspark_tpu.train.checkpoint import Checkpointer
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 13, 16, 8, 12
cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
opt = make_optimizer("adam", 1e-2)  # adam: momenta are PP-sharded too
mesh = make_mesh(dp=2, pp=2)  # 4 global devices, 2 per process

stacked = stack_lm_params(init_lm(jax.random.PRNGKey(0), cfg))
placed = place_pp_lm_params(stacked, mesh)
step = make_pp_lm_train_step(cfg, opt, mesh, stacked, microbatches=2,
                             donate=False)
state = init_train_state(placed, opt, jax.random.PRNGKey(1))

rng = np.random.RandomState(0)
from jax.sharding import NamedSharding, PartitionSpec as P
batch_host = {
    "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
    "targets": rng.randint(0, V, (B, T)).astype(np.int32),
}
batch = jax.tree.map(
    lambda a: jax.make_array_from_callback(
        a.shape, NamedSharding(mesh, P("data")), lambda idx: a[idx]
    ),
    batch_host,
)
state, m = step(state, batch)   # step 1: PP-sharded params + adam moments

ck = Checkpointer(ckpt_dir)
ck.save(state)                  # per-process shard files + marker

# fresh template with DIFFERENT values, same structure/shardings
stacked2 = stack_lm_params(init_lm(jax.random.PRNGKey(7), cfg))
template = init_train_state(place_pp_lm_params(stacked2, mesh), opt,
                            jax.random.PRNGKey(8))
restored = ck.restore_latest(template)
assert restored is not None
assert int(jax.device_get(restored.step)) == 1

# every local shard must round-trip exactly (scalar leaves like the adam
# step count restore as host numpy — compare values directly)
def check(a, b):
    if hasattr(a, "addressable_shards") and hasattr(b, "addressable_shards"):
        for sa, sb in zip(a.addressable_shards, b.addressable_shards):
            np.testing.assert_array_equal(np.asarray(sa.data),
                                          np.asarray(sb.data))
    else:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        )
jax.tree.map(check, state.params, restored.params)
jax.tree.map(check, state.opt_state, restored.opt_state)

# and the restored state must be trainable (chains into the step)
restored2, m2 = step(restored, batch)
state2, m_want = step(state, batch)
assert abs(float(m2["loss"]) - float(m_want["loss"])) < 1e-6
print(f"proc {pid}: sharded checkpoint round-trip ok", flush=True)
'''


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_dp_training_parity(tmp_path):
    port = str(_free_port())
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:  # never leave orphans holding the coordinator port
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "matches single" in out


@pytest.mark.skipif(os.environ.get("LSTM_TSP_SKIP_MULTIPROC") == "1",
                    reason="multiprocess smoke disabled")
def test_two_process_pp_sharded_checkpoint(tmp_path):
    """Multi-host-safe checkpointing (VERDICT r1 weak #6): 2 real processes,
    PP-sharded params + adam moments; per-process shard files, marker-gated
    restorability, reshard-on-restore, and trainability of the result."""
    port = str(_free_port())
    ckpt = str(tmp_path / "ckpt")
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _CKPT_WORKER, str(i), port, ckpt],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "round-trip ok" in out
    # both processes wrote their own shard file; step marked complete
    names = os.listdir(ckpt)
    assert "step_1.complete" in names
    assert sum(1 for n in names if n.startswith("step_1.proc")) == 2
