"""LMConfig.logits_dtype — the opt-in bf16 [B,T,V] logits array.

Measured on v5e (config 3, V=33k): every pass over the materialized
logits array is an HBM-bandwidth cost; bf16 halves five of them for +25%
step throughput. These tests pin the semantics: default float32 is
bit-identical to the pre-option code, bf16 keeps the loss within bf16
rounding of the f32 loss, gradients stay finite and close, and every
parallel path (DP / sharded TP / PP) respects the config so the
sharded-vs-single parity law holds per setting.
"""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss


def _batch(V, B=4, T=12, seed=0):
    k = jax.random.PRNGKey(seed)
    toks = jax.random.randint(k, (B, T + 1), 0, V, jnp.int32)
    return {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


def test_default_is_float32_and_unchanged():
    cfg = LMConfig(vocab_size=50, hidden_size=16)
    assert cfg.ldtype == jnp.float32
    params = init_lm(jax.random.PRNGKey(1), cfg)
    batch = _batch(50)
    l1, _ = lm_loss(params, batch, cfg)
    l2, _ = lm_loss(params, batch,
                    LMConfig(vocab_size=50, hidden_size=16,
                             logits_dtype="float32"))
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def test_bf16_logits_loss_close_and_grads_finite():
    cfg32 = LMConfig(vocab_size=200, hidden_size=32)
    cfg16 = LMConfig(vocab_size=200, hidden_size=32,
                     logits_dtype="bfloat16")
    params = init_lm(jax.random.PRNGKey(2), cfg32)
    batch = _batch(200, seed=3)

    l32, _ = lm_loss(params, batch, cfg32)
    l16, _ = lm_loss(params, batch, cfg16)
    # logits magnitudes are O(1) at init; bf16 rounding is ~0.4% relative
    np.testing.assert_allclose(np.asarray(l16), np.asarray(l32), rtol=2e-2)

    g = jax.grad(lambda p: lm_loss(p, batch, cfg16)[0])(params)
    for leaf in jax.tree.leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_bf16_logits_sharded_paths_match_single():
    """sp_lm_loss (the TP/SP/3D loss body) must produce the same loss as
    lm_loss under the SAME logits_dtype — the parity law the sharded
    tests rely on, now parameterized by the new field."""
    from jax.sharding import Mesh

    cfg = LMConfig(vocab_size=60, hidden_size=16,
                   logits_dtype="bfloat16")
    params = init_lm(jax.random.PRNGKey(4), cfg)
    batch = _batch(60, seed=5)

    ref, _ = lm_loss(params, batch, cfg)

    from lstm_tensorspark_tpu.parallel.train_step import sp_lm_loss

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1, 1, 1),
                ("data", "model", "seq", "pipe"))
    with mesh:
        try:
            from jax import shard_map as smap
        except ImportError:
            from jax.experimental.shard_map import shard_map as smap
        from jax.sharding import PartitionSpec as P

        f = smap(
            lambda p, b: sp_lm_loss(p, b, cfg)[0],
            mesh=mesh,
            in_specs=(P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        got = f(params, batch)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
