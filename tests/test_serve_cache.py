"""State-cache tests (serve/state_cache.py): slot lifecycle, LRU eviction,
pinning, and the detach/restore round trip — continued decode after a
detach must be token-identical to an uninterrupted run.

The jit-touching tests share one module-scoped engine (and one reference
`make_generate_fn` program) so the file pays each XLA compile once —
tier-1 wall-clock discipline."""

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.serve import (
    Batcher,
    CacheFullError,
    Request,
    ServeEngine,
    StateCache,
)


def test_slot_reuse_after_release():
    cache = StateCache(num_layers=1, num_slots=2, hidden_size=4)
    slot_a, fresh = cache.acquire("a")
    assert fresh
    cache.release("a")
    slot_b, fresh = cache.acquire("b")
    assert fresh
    assert slot_b == slot_a  # released slot recycled
    # re-acquire of a live session is not fresh and keeps its slot
    slot_b2, fresh = cache.acquire("b")
    assert (slot_b2, fresh) == (slot_b, False)


def test_lru_eviction_order():
    cache = StateCache(num_layers=1, num_slots=2, hidden_size=4)
    cache.acquire("a")
    cache.acquire("b")
    cache.lookup("a")  # refresh a → b becomes least-recently-used
    cache.acquire("c")  # full: must evict b
    assert "b" not in cache and "a" in cache and "c" in cache
    assert cache.evictions == 1


def test_pinned_slots_never_evicted():
    cache = StateCache(num_layers=1, num_slots=2, hidden_size=4)
    cache.acquire("a")
    cache.acquire("b")
    cache.pin("a")
    cache.pin("b")
    with pytest.raises(CacheFullError):
        cache.acquire("c")
    cache.unpin("b")
    cache.acquire("c")  # now b (unpinned LRU) is evictable
    assert "b" not in cache and "a" in cache


def test_scratch_slot_is_outside_the_slot_space():
    cache = StateCache(num_layers=2, num_slots=3, hidden_size=4)
    assert cache.scratch_slot == 3
    assert cache.h.shape == (2, 4, 4)  # num_slots + 1 rows


def test_detach_restore_preserves_values():
    cache = StateCache(num_layers=2, num_slots=2, hidden_size=3)
    slot, _ = cache.acquire("s")
    h = np.arange(6, dtype=np.float32).reshape(2, 1, 3)
    c = -h
    cache.write_slots(np.asarray([slot]), h, c)
    state = cache.detach("s")
    assert "s" not in cache
    np.testing.assert_array_equal(state.h, h[:, 0, :])
    np.testing.assert_array_equal(state.c, c[:, 0, :])
    cache.acquire("other")  # may take the old slot: restore must still work
    new_slot = cache.restore("s", state)
    got_h, got_c = cache.read_slots(np.asarray([new_slot]))
    np.testing.assert_array_equal(np.asarray(got_h), h)
    np.testing.assert_array_equal(np.asarray(got_c), c)


def test_restore_rejects_wrong_shape():
    cache = StateCache(num_layers=2, num_slots=2, hidden_size=3)
    bad = np.zeros((1, 3), np.float32)
    from lstm_tensorspark_tpu.serve.state_cache import DetachedState

    with pytest.raises(ValueError):
        cache.restore("x", DetachedState(h=bad, c=bad))


# ---- decode-parity tests: one shared engine + one reference program -----

_CFG = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)
_PROMPT = np.array([3, 5, 7, 2, 11], np.int32)
_N_TOTAL = 10


@pytest.fixture(scope="module")
def stack():
    params = init_lm(jax.random.PRNGKey(0), _CFG)
    engine = ServeEngine(
        params, _CFG, num_slots=8,
        prefill_buckets=(8, 16), batch_buckets=(1, 2, 4),
    )
    return params, engine


@pytest.fixture(scope="module")
def ref_tokens(stack):
    """Uninterrupted greedy reference: _N_TOTAL tokens for _PROMPT."""
    params, _ = stack
    return np.asarray(
        make_generate_fn(_CFG, max_new_tokens=_N_TOTAL, greedy=True)(
            params, _PROMPT[None, :], jax.random.PRNGKey(0)
        )
    )[0, _PROMPT.size:]


def test_detach_restore_roundtrip_equals_uncached_decode(stack, ref_tokens):
    """Split a greedy decode at token k, detach the session to host,
    restore, continue — the concatenation must equal one uninterrupted
    models/generate.py run."""
    _, engine = stack
    batcher = Batcher(engine, max_active=4, queue_size=8)
    k = 4

    first = Request(_PROMPT, k, keep_session=True)
    batcher.submit(first)
    batcher.drain()
    assert first.error is None
    sid = first.session_id
    assert sid is not None and sid in engine.cache

    detached = engine.detach_session(sid)
    assert sid not in engine.cache
    # churn the cache while the session lives on host: other sessions are
    # free to take (and dirty) its old slot
    churn = Request(np.array([1, 2, 3], np.int32), 3)
    batcher.submit(churn)
    batcher.drain()

    engine.restore_session(sid, detached)
    # continuation feeds the last generated token; carries resume exactly
    second = Request(np.array([first.tokens[-1]], np.int32), _N_TOTAL - k,
                     session_id=sid)
    batcher.submit(second)
    batcher.drain()
    assert second.error is None
    engine.cache.release(sid)

    got = np.asarray(first.tokens + second.tokens, np.int32)
    np.testing.assert_array_equal(got, ref_tokens)


def test_kept_session_continues_without_detach(stack, ref_tokens):
    """keep_session alone (no detach) also continues exactly."""
    _, engine = stack
    batcher = Batcher(engine, max_active=4, queue_size=8)
    a = Request(_PROMPT, 2, keep_session=True)
    batcher.submit(a)
    batcher.drain()
    b = Request(np.array([a.tokens[-1]], np.int32), 4, session_id=a.session_id)
    batcher.submit(b)
    batcher.drain()
    np.testing.assert_array_equal(np.asarray(a.tokens + b.tokens),
                                  ref_tokens[:6])
    engine.cache.release(a.session_id)


def test_evicted_session_continuation_fails_loudly():
    cfg = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(params, cfg, num_slots=1,
                         prefill_buckets=(8,), batch_buckets=(1,))
    batcher = Batcher(engine, max_active=1, queue_size=8)
    a = Request(np.array([1, 2], np.int32), 2, keep_session=True)
    batcher.submit(a)
    batcher.drain()
    # the only slot gets recycled by a new session → a's state is evicted
    b = Request(np.array([3, 4], np.int32), 2)
    batcher.submit(b)
    batcher.drain()
    cont = Request(np.array([a.tokens[-1]], np.int32), 2,
                   session_id=a.session_id)
    batcher.submit(cont)
    batcher.drain()
    assert cont.error is not None and "expired" in cont.error
