"""Test fixtures: run everything on a virtual 8-device CPU mesh.

The moral equivalent of the reference's Spark ``local[N]`` story
(SURVEY.md §4): distributed topology simulated on one host. Must set env
before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize force-registers the TPU ("axon") platform
# and overrides JAX_PLATFORMS; push the config back to CPU-only so the 8
# virtual devices take effect.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

assert jax.device_count() == 8, jax.devices()
