"""Exit-code contract (resilience/exit_codes.py) and its consumers:
uniqueness of the table, and chip_recovery's rc-first wedge routing
(ADVICE r5 finding 1, closed properly: the dedicated liveness rc routes a
wedge-shaped bench failure without scanning stdout)."""

import os
import sys

import pytest

from lstm_tensorspark_tpu.resilience import exit_codes as ec

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


def test_codes_are_unique_and_in_range():
    codes = [ec.USAGE_RC, ec.REGRESSION_RC, ec.CHILD_FAIL_RC, ec.WEDGE_RC,
             ec.LIVENESS_RC, ec.ANOMALY_RC, ec.POISON_RC, ec.FAULT_CRASH_RC]
    assert len(set(codes)) == len(codes)  # no collisions, ever again
    assert all(0 < c < 128 for c in codes)  # never masquerade as a signal
    assert ec.RETRYABLE_RCS <= set(codes)
    assert ec.POISON_RC not in ec.RETRYABLE_RCS  # poison means STOP


class _FakeCompleted:
    def __init__(self, rc, stdout="", stderr=""):
        self.returncode = rc
        self.stdout = stdout
        self.stderr = stderr


@pytest.fixture()
def chip_recovery(monkeypatch):
    import chip_recovery as cr

    return cr


def _patch_run(monkeypatch, cr, result):
    monkeypatch.setattr(cr.subprocess, "run", lambda *a, **k: result)


def test_liveness_rc_routes_to_wedge_without_marker(monkeypatch, chip_recovery):
    """The dedicated rc alone is enough — no marker string in the output."""
    _patch_run(monkeypatch, chip_recovery,
               _FakeCompleted(ec.LIVENESS_RC, stdout="{\"value\": 0.0}"))
    with pytest.raises(SystemExit) as ei:
        chip_recovery._run(["bench"], timeout=1, label="t", scan_wedge=True)
    assert ei.value.code == ec.WEDGE_RC


def test_marker_scan_survives_as_legacy_fallback(monkeypatch, chip_recovery):
    _patch_run(monkeypatch, chip_recovery,
               _FakeCompleted(3, stdout="... unreachable/wedged ..."))
    with pytest.raises(SystemExit) as ei:
        chip_recovery._run(["bench"], timeout=1, label="t", scan_wedge=True)
    assert ei.value.code == ec.WEDGE_RC


def test_plain_failure_is_child_fail_not_wedge(monkeypatch, chip_recovery):
    """rc=3 WITHOUT the marker is the regression gate — a persistent
    failure, must NOT loop the watcher's probe path."""
    _patch_run(monkeypatch, chip_recovery,
               _FakeCompleted(3, stdout="regression on imdb_bilstm"))
    with pytest.raises(SystemExit) as ei:
        chip_recovery._run(["bench"], timeout=1, label="t", scan_wedge=True)
    assert ei.value.code == ec.CHILD_FAIL_RC


def test_measure_routes_liveness_rc_to_wedge(monkeypatch, chip_recovery):
    _patch_run(monkeypatch, chip_recovery,
               _FakeCompleted(ec.LIVENESS_RC, stdout="", stderr="dead"))
    with pytest.raises(SystemExit) as ei:
        chip_recovery._measure("ptb_char")
    assert ei.value.code == ec.WEDGE_RC
