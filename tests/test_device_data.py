"""Device-resident dataset (data/device_dataset.py + train/device_step.py):
window slicing must reproduce the host-fed stream exactly, and the K-step
device-data training must be bit-identical to host-fed training — single
chip and DP."""

import jax
import numpy as np

from lstm_tensorspark_tpu.data import (
    lm_batch_stream,
    slice_window,
    stacked_batches,
    stage_lm_data,
    window_index_stream,
)
from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_mesh, shard_batch
from lstm_tensorspark_tpu.parallel.data_parallel import replicate
from lstm_tensorspark_tpu.train import (
    make_device_dp_lm_train_step,
    make_device_lm_train_step,
    make_dp_multi_train_step,
    make_multi_train_step,
    make_optimizer,
)
from lstm_tensorspark_tpu.train.loop import init_train_state

B, T, V, H, K = 8, 16, 29, 16, 4


def _tokens(n=B * T * 12 + 1):
    return np.random.RandomState(0).randint(0, V, n).astype(np.int32)


def _cfg():
    return LMConfig(vocab_size=V, hidden_size=H, num_layers=2)


def test_slice_window_matches_host_stream():
    tokens = _tokens()
    data = stage_lm_data(tokens, B, T)
    host = list(lm_batch_stream(tokens, B, T, num_epochs=1))
    assert len(host) == data.n_windows
    for w, hb in enumerate(host):
        dev = jax.jit(lambda a, w: slice_window(a, w, T))(
            data.arrays, np.int32(w)
        )
        np.testing.assert_array_equal(np.asarray(dev["inputs"]), hb["inputs"])
        np.testing.assert_array_equal(np.asarray(dev["targets"]), hb["targets"])


def test_device_data_matches_host_fed_training():
    tokens = _tokens()
    cfg = _cfg()

    def loss_fn(p, b, r):
        return lm_loss(p, b, cfg)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)

    host_step = make_multi_train_step(loss_fn, opt)
    s_host = init_train_state(params, opt, jax.random.PRNGKey(1))
    host_it = stacked_batches(lm_batch_stream(tokens, B, T), K)
    for _ in range(5):
        s_host, m_host = host_step(s_host, next(host_it))

    data = stage_lm_data(tokens, B, T)
    dev_step = make_device_lm_train_step(loss_fn, opt, data, steps_per_call=K)
    s_dev = init_train_state(params, opt, jax.random.PRNGKey(1))
    idx = window_index_stream(data, K)
    for _ in range(5):
        s_dev, m_dev = dev_step(s_dev, data.arrays, next(idx))

    np.testing.assert_allclose(float(m_host["loss"]), float(m_dev["loss"]), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        jax.device_get(s_host.params), jax.device_get(s_dev.params),
    )


def test_device_data_wraps_epochs():
    """Host stream wraps epochs by restarting; the window index stream must
    visit the same windows in the same order across the wrap."""
    tokens = _tokens(B * T * 3 + 1)  # 3 windows; K=4 wraps mid-call
    data = stage_lm_data(tokens, B, T)
    assert data.n_windows == 3
    idx = window_index_stream(data, K)
    starts = [int(next(idx)) for _ in range(4)]
    assert starts == [0, 1, 2, 0]  # (0+4)%3=1, (1+4)%3=2, ...


def test_device_data_dp_matches_single():
    tokens = _tokens()
    cfg = _cfg()

    def loss_fn(p, b, r):
        return lm_loss(p, b, cfg)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)

    data1 = stage_lm_data(tokens, B, T)
    step1 = make_device_lm_train_step(loss_fn, opt, data1, steps_per_call=K)
    s1 = init_train_state(params, opt, jax.random.PRNGKey(1))
    idx1 = window_index_stream(data1, K)
    for _ in range(3):
        s1, m1 = step1(s1, data1.arrays, next(idx1))

    mesh = make_mesh(dp=4, devices=np.asarray(jax.devices()[:4]))
    data4 = stage_lm_data(tokens, B, T, mesh=mesh)
    step4 = make_device_dp_lm_train_step(loss_fn, opt, data4, mesh, steps_per_call=K)
    s4 = init_train_state(replicate(params, mesh), opt, jax.random.PRNGKey(1))
    idx4 = window_index_stream(data4, K)
    for _ in range(3):
        s4, m4 = step4(s4, data4.arrays, next(idx4))

    # same global batch (streams sharded by row), grads pmean'd → same update
    np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        jax.device_get(s1.params), jax.device_get(s4.params),
    )


def test_device_data_stateful_matches_host():
    """Stateful TBPTT carries stay aligned (stream order is identical)."""
    from lstm_tensorspark_tpu.models.lstm_lm import init_carries

    tokens = _tokens()
    cfg = _cfg()

    def loss_fn(p, b, r, carries):
        return lm_loss(p, b, cfg, carries=carries)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    carries0 = init_carries(cfg, B)

    host_step = make_multi_train_step(loss_fn, opt, stateful=True)
    s_host = init_train_state(params, opt, jax.random.PRNGKey(1), carries=carries0)
    host_it = stacked_batches(lm_batch_stream(tokens, B, T), K)
    for _ in range(4):
        s_host, _ = host_step(s_host, next(host_it))

    data = stage_lm_data(tokens, B, T)
    dev_step = make_device_lm_train_step(
        loss_fn, opt, data, steps_per_call=K, stateful=True
    )
    s_dev = init_train_state(params, opt, jax.random.PRNGKey(1), carries=carries0)
    idx = window_index_stream(data, K)
    for _ in range(4):
        s_dev, _ = dev_step(s_dev, data.arrays, next(idx))

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        jax.device_get(s_host.params), jax.device_get(s_dev.params),
    )


def test_forecast_device_matches_host(tmp_path):
    """Forecaster --device-data must produce the identical loss stream to
    the host-fed path (same shuffled window order)."""
    import json

    from lstm_tensorspark_tpu.cli import main

    common = [
        "--dataset", "uci_electricity", "--hidden-units", "16",
        "--batch-size", "8", "--seq-len", "24", "--num-steps", "4",
        "--log-every", "1", "--backend", "single",
        "--compute-dtype", "float32", "--learning-rate", "0.1",
    ]
    host_log, dev_log = tmp_path / "host.jsonl", tmp_path / "dev.jsonl"
    assert main([*common, "--jsonl", str(host_log)]) == 0
    assert main([*common, "--device-data", "--steps-per-call", "2",
                 "--jsonl", str(dev_log)]) == 0

    def losses(p):
        return [r["loss"] for r in map(json.loads, p.read_text().splitlines())
                if "loss" in r]

    h, d = losses(host_log), losses(dev_log)
    # device path logs K-step means; compare the final eval instead
    def final_mse(p):
        recs = [json.loads(l) for l in p.read_text().splitlines()]
        return [r["eval_mse"] for r in recs if "eval_mse" in r][-1]

    np.testing.assert_allclose(final_mse(host_log), final_mse(dev_log),
                               rtol=1e-5)
    assert h and d


def test_classifier_device_matches_host(tmp_path):
    """Classifier --device-data: identical final accuracy/loss to host-fed
    (same shuffle+bucket order, gathers reproduce padded rows)."""
    import json

    from lstm_tensorspark_tpu.cli import main

    common = [
        "--dataset", "imdb", "--hidden-units", "16", "--batch-size", "16",
        "--seq-len", "40", "--num-steps", "6", "--log-every", "1",
        "--backend", "single", "--compute-dtype", "float32",
        "--optimizer", "adam", "--learning-rate", "1e-2",
    ]
    host_log, dev_log = tmp_path / "host.jsonl", tmp_path / "dev.jsonl"
    assert main([*common, "--jsonl", str(host_log)]) == 0
    assert main([*common, "--device-data", "--steps-per-call", "3",
                 "--jsonl", str(dev_log)]) == 0

    def final(p, key):
        recs = [json.loads(l) for l in p.read_text().splitlines()]
        return [r[key] for r in recs if key in r][-1]

    np.testing.assert_allclose(
        final(host_log, "eval_loss"), final(dev_log, "eval_loss"), rtol=1e-4
    )
    np.testing.assert_allclose(
        final(host_log, "eval_accuracy"), final(dev_log, "eval_accuracy"),
        rtol=1e-6,
    )


def test_forecast_device_dp_runs():
    """Forecaster device-data under DP (replicated series, sharded starts)."""
    from lstm_tensorspark_tpu.cli import main

    rc = main([
        "--dataset", "uci_electricity", "--hidden-units", "16",
        "--batch-size", "16", "--seq-len", "24", "--num-steps", "4",
        "--log-every", "2", "--num-partitions", "4", "--device-data",
        "--steps-per-call", "2", "--compute-dtype", "float32",
    ])
    assert rc == 0
