"""Serve-side telemetry: /metrics exposition over the real HTTP endpoint,
histogram summaries in /stats, per-request phase breakdowns, server-vs-
loadgen latency agreement, and the --trace request timelines.

One module-scoped server with its OWN MetricsRegistry (not the process
default) so every assertion reads exactly this stack's telemetry.
"""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.obs import (
    DEFAULT_LATENCY_BUCKETS,
    NULL_REGISTRY,
    MetricsRegistry,
    parse_exposition,
)
from lstm_tensorspark_tpu.serve import ServeEngine, ServeServer, run_loadgen
from lstm_tensorspark_tpu.utils import Tracer, set_tracer

_CFG = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)


def _build(registry):
    params = init_lm(jax.random.PRNGKey(3), _CFG)
    engine = ServeEngine(
        params, _CFG, num_slots=8,
        prefill_buckets=(4, 8), batch_buckets=(1, 2, 4),
        registry=registry,
    )
    return ServeServer(engine, max_active=4, queue_size=16)


@pytest.fixture(scope="module")
def stack():
    reg = MetricsRegistry()
    server = _build(reg)
    server.start()
    yield reg, server
    server.stop()


def test_metrics_route_serves_valid_exposition(stack):
    from lstm_tensorspark_tpu.serve.server import make_http_server

    reg, server = stack
    httpd = make_http_server(server, port=0)
    host, port = httpd.server_address[:2]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        base = f"http://{host}:{port}"
        body = json.dumps({"prompt": [5, 1, 2], "max_new_tokens": 6,
                           "greedy": True}).encode()
        req = urllib.request.Request(
            base + "/v1/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            ctype = r.headers["Content-Type"]
            text = r.read().decode()
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
    finally:
        httpd.shutdown()
        httpd.server_close()

    assert ctype.startswith("text/plain")
    fams = parse_exposition(text)  # raises on any format violation
    # the headline server-side distributions are all present as histograms
    for name in ("serve_ttft_seconds", "serve_itl_seconds",
                 "serve_queue_wait_seconds",
                 "serve_scheduler_iteration_seconds"):
        assert fams[name]["type"] == "histogram", name
        count = next(v for n, _, v in fams[name]["samples"]
                     if n == name + "_count")
        assert count >= 1, name
    # compile counters carry the phase label
    phases = {labels["phase"] for _, labels, _
              in fams["serve_compiles_total"]["samples"]}
    assert {"prefill", "decode"} <= phases
    assert fams["serve_requests_total"]["type"] == "counter"

    # the HTTP reply carries the per-request phase breakdown
    assert out["phases_ms"].get("queue_ms") is not None
    assert out["phases_ms"].get("prefill_ms", 0) > 0
    assert "decode_ms" in out["phases_ms"]

    # /stats (the JSON alias) now embeds histogram summaries
    ms = stats["metrics"]
    assert ms["serve_ttft_seconds"]["count"] >= 1
    assert "p50" in ms["serve_ttft_seconds"]
    assert "p99" in ms["serve_ttft_seconds"]


def _bucket_span(value_s: float) -> float:
    """Width of the DEFAULT_LATENCY_BUCKETS bucket containing value_s —
    the histogram's resolution at that point, hence the agreement bound."""
    lo = 0.0
    for hi in DEFAULT_LATENCY_BUCKETS:
        if value_s <= hi:
            return hi - lo
        lo = hi
    return float("inf")


def test_server_percentiles_agree_with_loadgen():
    """Server-side TTFT/ITL histograms and loadgen's sorted-sample
    percentiles observe the SAME timestamps, so they must agree to within
    the histogram's bucket resolution (the only quantization between
    them). Fresh registry + warmed server: the histograms then hold
    exactly this run's samples (no compile-inflated outliers)."""
    reg = MetricsRegistry()
    server = _build(reg)
    with server:
        server.warmup(prompt_lens=(4,))
        report = run_loadgen(server, vocab_size=_CFG.vocab_size, sessions=3,
                             requests_per_session=3, prompt_len=4,
                             max_new_tokens=6)
    assert report["failed"] == 0 and report["rejected"] == 0
    # every completed request's TTFT landed in the server histogram
    # (serve families carry a replica label; this stack is replica 0)
    h_ttft = reg.histogram("serve_ttft_seconds",
                           labelnames=("replica",)).labels(replica="0")
    assert h_ttft.snapshot()[2] == report["completed"]

    # loadgen embeds the server-side summaries next to its own numbers
    assert "server_histograms" in report
    assert report["server_histograms"]["serve_ttft_seconds"]["count"] >= 9

    for loadgen_key, name in (("p50_ttft_ms", "serve_ttft_seconds"),
                              ("p50_itl_ms", "serve_itl_seconds"),
                              ("p99_itl_ms", "serve_itl_seconds")):
        lg_s = report[loadgen_key] / 1e3
        q = 0.99 if loadgen_key.startswith("p99") else 0.5
        srv_s = reg.histogram(name, labelnames=("replica",)).labels(
            replica="0").quantile(q)
        tol = _bucket_span(lg_s) + 0.005  # bucket resolution + sched noise
        assert abs(srv_s - lg_s) <= tol, (loadgen_key, srv_s, lg_s, tol)


def test_trace_carries_request_timeline(tmp_path):
    """--trace on a serve run: every request gets a complete
    admit→queue→prefill→decode→readback timeline on its own named row."""
    server = _build(MetricsRegistry())
    tracer = Tracer()
    set_tracer(tracer)
    try:
        with server:
            reqs = [server.generate([1, 2, 3], max_new_tokens=6),
                    server.generate([4, 5], max_new_tokens=4)]
    finally:
        set_tracer(None)
    path = tmp_path / "serve_trace.json"
    tracer.save(str(path))
    events = json.loads(path.read_text())["traceEvents"]
    for req in reqs:
        row = [e for e in events
               if e.get("args", {}).get("request") == req.id]
        names = {e["name"] for e in row}
        assert {"queue", "prefill"} <= names, names
        assert "decode" in names or "decode_window" in names, names
        # windowed decode also shows the fetch-blocked readback slice
        if "decode_window" in names:
            assert "readback" in names
        # one named row per request
        assert any(e["ph"] == "M" and e["args"]["name"] == f"request {req.id}"
                   for e in events)
        # and the blocking phases cover positive time
        total = req.phase_summary_ms()
        assert total.get("prefill_ms", 0) > 0


def test_null_registry_disables_serve_telemetry():
    """--telemetry off: the stack records nothing, /metrics says so, and
    requests still serve (the no-op instruments are the whole cost)."""
    server = _build(NULL_REGISTRY)
    with server:
        req = server.generate([1, 2, 3], max_new_tokens=4)
    assert len(req.tokens) == 4
    assert server.metrics_summary() == {}
    assert "disabled" in server.metrics_text()


def test_registry_counters_track_stats_counters():
    """Cache/prefix counters flow through the registry: the /metrics view
    and the legacy stats() ints advance together."""
    reg = MetricsRegistry()
    params = init_lm(jax.random.PRNGKey(3), _CFG)
    engine = ServeEngine(params, _CFG, num_slots=4,
                         prefill_buckets=(4, 8), batch_buckets=(1, 2),
                         prefix_cache=True, prefix_stride=2,
                         registry=reg)
    server = ServeServer(engine, max_active=2, queue_size=8)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    with server:
        server.generate(prompt, max_new_tokens=2)  # cold: miss + insert
        server.generate(prompt, max_new_tokens=2)  # hot: hit
    st = engine.prefix.stats()
    fam = reg.counter("serve_prefix_cache_events_total",
                      labelnames=("event",))
    assert fam.labels(event="hit").value == st["hits"] >= 1
    assert fam.labels(event="miss").value == st["misses"] >= 1
    assert fam.labels(event="insert").value == st["inserts"] >= 1
    swaps = reg.counter("serve_state_cache_swaps_total").value
    assert swaps == engine.cache.stats()["generation"] > 0
