"""Request deadlines end-to-end (serve robustness plane): queue-expiry
reaping without a slot or prefill spent, decode-window-boundary expiry
with honest partial output, per-class default deadlines, SLO-aware
class shedding with Retry-After, weighted dequeue, the loadgen client's
Retry-After-honoring backoff, and the uniform HTTP error-body contract.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.obs import MetricsRegistry
from lstm_tensorspark_tpu.serve import (
    DeadlineExceededError,
    QueueFullError,
    Request,
    ServeEngine,
    ServeServer,
    run_loadgen,
)

_CFG = LMConfig(vocab_size=29, hidden_size=16, num_layers=1)


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(3), _CFG)


def _server(params, registry=None, n=1, batch_buckets=(1, 2, 4), **kw):
    reg = registry if registry is not None else MetricsRegistry()
    engines = [
        ServeEngine(params, _CFG, num_slots=8, prefill_buckets=(4, 8),
                    batch_buckets=batch_buckets, rng_seed=i, registry=reg)
        for i in range(n)
    ]
    kw.setdefault("max_active", 4)
    kw.setdefault("queue_size", 8)
    return ServeServer(engines if n > 1 else engines[0], **kw)


# ---- queue-expiry reaping (the no-wasted-prefill contract) -------------


def test_queue_expired_request_reaped_without_slot_or_prefill(params):
    """A request whose deadline lapses while QUEUED settles as a timeout
    without ever consuming a state-cache slot or a prefill dispatch, its
    serve_requests_total{outcome="timeout"} counter increments, and its
    phase timeline records the queue-only lifetime."""
    reg = MetricsRegistry()
    server = _server(params, registry=reg)
    b = server.batcher
    req = Request([1, 2, 3], 4, deadline_s=0.05)
    b.submit(req)
    assert req.deadline is not None  # stamped at submission
    time.sleep(0.12)
    cache_before = server.engine.cache.stats()
    prefills_before = server.engine.num_compiles("prefill")
    b.step()  # unstarted server: the test drives the scheduler directly
    assert req.done.is_set() and req.timed_out
    assert req.error is None and req.tokens == []
    # no slot was acquired, no prefill program dispatched
    after = server.engine.cache.stats()
    assert after["live_sessions"] == cache_before["live_sessions"]
    assert after["pinned"] == cache_before["pinned"]
    assert server.engine.num_compiles("prefill") == prefills_before
    # phase timeline: the queue-only lifetime, nothing else
    assert [p[0] for p in req.phases] == ["queue"]
    assert req.phases[0][1] == req.t_submit
    s = reg.summaries()
    assert s['serve_requests_total{outcome="timeout",replica="0"}'] == 1
    assert s['serve_deadline_expired_total{stage="queue",replica="0"}'] == 1
    assert b.stats()["timed_out"] == 1


def test_queue_expiry_reaps_behind_the_head(params):
    """Expiry is reaped from ANYWHERE in the queue, not just the head —
    a live long-deadline request ahead of it must not shield it."""
    server = _server(params)
    b = server.batcher
    live = Request([1, 2], 2)
    doomed = Request([3, 4], 2, deadline_s=0.05)
    b.submit(live)
    b.submit(doomed)
    time.sleep(0.12)
    b.drain()
    assert doomed.timed_out and doomed.tokens == []
    assert not live.timed_out and len(live.tokens) == 2


def test_decode_boundary_expiry_returns_partial_output(params):
    """A deadline lapsing mid-decode settles at the next window boundary
    with the tokens already generated — partial output, own outcome,
    never a wedged client; the session is not kept."""
    server = _server(params)
    with server:
        with pytest.raises(DeadlineExceededError) as ei:
            server.generate([1, 2, 3], max_new_tokens=100000,
                            deadline_s=0.15, keep_session=True,
                            timeout=30.0)
    req = ei.value.request
    assert req.timed_out
    assert 0 < len(req.tokens) < 100000  # partial, not empty, not full
    # not kept: the slot was released (no live session remains)
    assert server.engine.cache.stats()["live_sessions"] == 0


def test_timed_out_kept_session_discards_tier_copies(params, tmp_path):
    """A kept session whose LATER turn times out with partial output is
    fully discarded — device slot AND tier copies. The tier checkpoint
    from the last COMPLETED turn lacks the partial tokens the client
    already displayed, so resurrecting it would silently decode an
    inconsistent conversation; the honest outcome is a loud
    "unknown session" on the next continuation."""
    reg = MetricsRegistry()
    engine = ServeEngine(params, _CFG, num_slots=8, prefill_buckets=(4, 8),
                         batch_buckets=(1, 2, 4), registry=reg,
                         session_dir=str(tmp_path))
    server = ServeServer(engine, max_active=4, queue_size=8)
    with server:
        r1 = server.generate([1, 2, 3], max_new_tokens=2,
                             keep_session=True, timeout=30.0)
        sid = r1.session_id
        engine.tiers.flush(timeout=15.0)  # turn-1 checkpoint on disk
        with pytest.raises(DeadlineExceededError) as ei:
            server.generate([r1.tokens[-1]], max_new_tokens=100000,
                            session_id=sid, keep_session=True,
                            deadline_s=0.2, timeout=30.0)
        assert len(ei.value.request.tokens) > 0  # partial output shown
        with pytest.raises(RuntimeError, match="unknown session"):
            server.generate([1], max_new_tokens=2, session_id=sid,
                            timeout=30.0)


def test_per_class_default_deadline_applied(params):
    server = _server(params,
                     deadline_defaults={"best_effort": 0.05})
    with server:
        # priority: no default — completes
        r = server.generate([1, 2, 3], max_new_tokens=2, timeout=30.0)
        assert len(r.tokens) == 2 and r.deadline_s is None
        # best_effort inherits the 50 ms default and times out on a
        # budget far larger than 50 ms of CPU decode
        with pytest.raises(DeadlineExceededError) as ei:
            server.generate([1, 2, 3], max_new_tokens=100000,
                            klass="best_effort", timeout=30.0)
        assert ei.value.request.deadline_s == 0.05


def test_explicit_zero_deadline_opts_out_of_default(params):
    """deadline_s <= 0 (the CLI's documented 0-means-none semantics) is
    an explicit opt-out of the per-class default — without it a client
    on a defaulted server could never request an unbounded run."""
    server = _server(params, deadline_defaults={"priority": 0.05})
    with server:
        with pytest.raises(DeadlineExceededError):
            server.generate([1, 2, 3], max_new_tokens=100000, timeout=30.0)
        r = server.generate([1, 2, 3], max_new_tokens=4, deadline_s=0,
                            timeout=30.0)
        assert len(r.tokens) == 4 and r.deadline_s is None


def test_default_loadgen_report_is_strict_json(params):
    """A default (single-class) run's always-present classes section
    must serialize as strict RFC-8259 JSON — the zero-traffic class
    reports null percentiles, never NaN."""
    server = _server(params)
    with server:
        report = run_loadgen(server, vocab_size=_CFG.vocab_size,
                             sessions=2, requests_per_session=1,
                             prompt_len=4, max_new_tokens=4)
    json.dumps(report, allow_nan=False)  # raises on any NaN/Inf
    assert report["classes"]["best_effort"]["p99_ttft_ms"] is None
    assert report["classes"]["priority"]["p99_ttft_ms"] is not None


def test_request_validates_class_and_deadline():
    with pytest.raises(ValueError):
        Request([1], 1, klass="vip")
    with pytest.raises(ValueError):
        Request([1], 1, deadline_s=0.0)


# ---- weighted dequeue + class shedding ---------------------------------


def test_weighted_dequeue_prefers_priority(params):
    """With both classes queued, one admission round serves them in the
    configured weight ratio (default 4:1) instead of pure FIFO."""
    server = _server(params, max_active=5, queue_size=16,
                     batch_buckets=(1, 2, 4, 8))  # capacity 5 fits a bucket
    b = server.batcher
    reqs = ([Request([1, 2], 1, klass="best_effort") for _ in range(5)]
            + [Request([1, 2], 1) for _ in range(5)])
    for r in reqs:
        b.submit(r)  # all best_effort submitted FIRST
    b.step()  # capacity 5: weighted pick must take 4 priority + 1 be
    done_p = sum(1 for r in reqs if r.klass == "priority"
                 and r.done.is_set())
    done_b = sum(1 for r in reqs if r.klass == "best_effort"
                 and r.done.is_set())
    assert (done_p, done_b) == (4, 1)
    b.drain()  # everyone is eventually served — weighted, not starved
    assert all(r.done.is_set() and r.error is None for r in reqs)


def test_router_sheds_best_effort_first_with_retry_after(params):
    """best_effort 429s at best_effort_frac * queue_size while priority
    keeps the full bound; sheds carry a positive retry_after_s and land
    in shed_by_class + serve_shed_total."""
    reg = MetricsRegistry()
    server = _server(params, registry=reg, queue_size=8,
                     best_effort_queue_frac=0.5)
    for _ in range(4):
        server.router.submit(Request([1, 2], 2))
    with pytest.raises(QueueFullError) as ei:
        server.router.submit(Request([1, 2], 2, klass="best_effort"))
    assert ei.value.retry_after_s and ei.value.retry_after_s > 0
    for _ in range(4):  # priority still admits up to the full bound
        server.router.submit(Request([1, 2], 2))
    with pytest.raises(QueueFullError) as ei2:
        server.router.submit(Request([1, 2], 2))
    assert ei2.value.retry_after_s and ei2.value.retry_after_s > 0
    st = server.router.stats()
    assert st["shed_by_class"] == {"priority": 1, "best_effort": 1}
    assert st["best_effort_bound"] == 4
    s = reg.summaries()
    assert s['serve_shed_total{class="best_effort",tenant_limited="no"}'] == 1
    assert s['serve_shed_total{class="priority",tenant_limited="no"}'] == 1
    assert s["serve_retry_after_seconds"]["count"] == 2


def test_batcher_level_429_also_carries_retry_after(params):
    """The per-replica queue bound (direct submits; a wedged replica's
    queue filling on the affinity path) honors the same contract as the
    router's shed: retry_after_s attached + serve_shed_total counted —
    no second-class 429s."""
    reg = MetricsRegistry()
    server = _server(params, registry=reg, queue_size=1)
    b = server.batcher
    b.submit(Request([1, 2], 2))
    with pytest.raises(QueueFullError) as ei:
        b.submit(Request([1, 2], 2, klass="best_effort"))
    assert ei.value.retry_after_s and ei.value.retry_after_s > 0
    s = reg.summaries()
    assert s['serve_shed_total{class="best_effort",tenant_limited="no"}'] == 1
    assert s["serve_retry_after_seconds"]["count"] == 1


def test_retry_after_scales_with_queue_wait_p99(params):
    """Retry-After is computed from the live queue-wait p99 histogram —
    a server whose queue recently waited ~2 s hints a retry near that,
    not a made-up constant."""
    reg = MetricsRegistry()
    server = _server(params, registry=reg, queue_size=4)
    # seed the queue-wait histogram the way 2 s waits would
    fam = reg.histogram("serve_queue_wait_seconds",
                        labelnames=("replica",))
    for _ in range(50):
        fam.labels(replica="0").observe(2.0)
    for _ in range(4):
        server.router.submit(Request([1, 2], 2))
    with pytest.raises(QueueFullError) as ei:
        server.router.submit(Request([1, 2], 2))
    # p99 estimate lands inside the (1.0, 2.5] bucket (~2.5), scaled by
    # the 1.5x full-queue factor — near 3.75 s, nowhere near the cold
    # 0.25 s floor. The point: the hint tracks the MEASURED wait.
    assert 2.0 <= ei.value.retry_after_s <= 4.5


def test_requeued_request_keeps_its_deadline(params):
    """A replica-death requeue must not reset the client's budget: the
    absolute deadline survives the second submit()."""
    server = _server(params)
    b = server.batcher
    req = Request([1, 2], 2, deadline_s=30.0)
    b.submit(req)
    deadline = req.deadline
    assert deadline is not None
    drained = b.drain_queue()
    assert drained == [req]
    b.submit(req)  # the router's requeue path re-enters here
    assert req.deadline == deadline
    assert b.stats()["submitted"] == 1  # not double-counted


# ---- loadgen client: Retry-After honoring + per-class report -----------


def test_loadgen_retries_sheds_with_backoff_and_reports_classes(params):
    """The loadgen client honors Retry-After (shared capped-backoff
    helper) and its JSON summary carries per-class shed/retried counts
    (the satellite contract)."""
    server = _server(params, queue_size=2, max_active=1,
                     best_effort_queue_frac=0.5)
    with server:
        report = run_loadgen(
            server, vocab_size=_CFG.vocab_size, sessions=6,
            requests_per_session=2, prompt_len=4, max_new_tokens=8,
            mode="open", rate=400.0, seed=0, priority_frac=0.5,
            retry_max=2, retry_base_s=0.01, retry_cap_s=0.1,
        )
    assert set(report["classes"]) == {"priority", "best_effort"}
    for cls in report["classes"].values():
        assert {"completed", "shed", "retried", "timeouts",
                "p99_ttft_ms"} <= set(cls)
    total_retried = sum(c["retried"] for c in report["classes"].values())
    assert total_retried >= 1  # the burst overruns queue_size=2
    # accounting closes: every request completed, shed, failed or timed out
    assert report["requests"] == (
        report["completed"] + report["rejected"] + report["failed"]
        + report["timeouts"])


# ---- uniform HTTP error bodies (satellite: stable client contract) -----


def _post(base, body, headers=None):
    req = urllib.request.Request(
        base + "/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_http_error_bodies_are_uniform(params):
    """Every non-200 reply carries the same machine-readable shape:
    error (message), code, retryable, retry_after_s — 429s also send the
    standard Retry-After header; deadline 504s carry partial tokens."""
    from lstm_tensorspark_tpu.serve.server import make_http_server

    server = _server(params, queue_size=2,
                     deadline_defaults={"best_effort": 0.15})
    httpd = make_http_server(server, port=0)
    host, port = httpd.server_address[:2]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://{host}:{port}"
    try:
        # 400: bad request
        code, _, body = _post(base, {"prompt": None})
        assert code == 400
        assert body["code"] == "bad_request" and body["retryable"] is False
        assert body["retry_after_s"] is None and "error" in body
        # 404: unknown route, same shape
        req = urllib.request.Request(base + "/nope")
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("404 expected")
        except urllib.error.HTTPError as e:
            nf = json.loads(e.read())
        assert nf["code"] == "not_found" and nf["retryable"] is False
        # 429 with Retry-After header: fill the UNSTARTED server's queue
        for _ in range(2):
            server.router.submit(Request([1, 2], 2))
        code, headers, body = _post(
            base, {"prompt": [1, 2], "max_new_tokens": 2})
        assert code == 429
        assert body["code"] == "queue_full" and body["retryable"] is True
        assert body["retry_after_s"] > 0
        assert int(headers["Retry-After"]) >= 1
        # 504 deadline_exceeded WITH partial tokens (the server must be
        # serving for decode to start; X-Deadline-S drives the deadline)
        with server:
            # drain the 429 section's stale queue + compile the programs
            # first, so the deadline budget is spent DECODING, not on
            # first-traffic XLA compiles
            code, _, warm = _post(
                base, {"prompt": [1, 2, 3], "max_new_tokens": 8,
                       "greedy": True})
            assert code == 200, warm
            code, _, body = _post(
                base, {"prompt": [1, 2, 3], "max_new_tokens": 100000,
                       "greedy": True},
                headers={"X-Deadline-S": "0.3"})
        assert code == 504
        assert body["code"] == "deadline_exceeded"
        assert body["retryable"] is True
        assert len(body["tokens"]) > 0  # the partial output rode along
    finally:
        httpd.shutdown()
        httpd.server_close()
