"""Network-partition tolerance for the remote-replica plane (ISSUE 17):
the shared retrying transport (serve/transport.py), per-peer circuit
breaker with flap damping and rejoin hysteresis, the peer-side settled
cache that makes the generate POST exactly-once over at-least-once
delivery, and the four injectable network fault kinds.

Pins: circuit state-machine transitions (alternating ok/fail below the
threshold NEVER opens — the flap-damping property; rejoin needs
consecutive probe successes); an alternating lossy heartbeat link never
retires the poller (retirement is refused-only); a duplicate generate
POST with the same request_id decodes exactly once (tokens identical,
``replayed`` marked, dedup hit counted); transport retries ride
``backoff_delay`` and a drop-then-replay round trip survives end to
end; circuit-open fail-fast never waits out the rpc timeout;
``generate_timeout_s`` validation (negative rejected, 0 = unbounded);
and the ``net_*`` fault grammar + hook semantics."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.obs import MetricsRegistry
from lstm_tensorspark_tpu.resilience import faults
from lstm_tensorspark_tpu.serve import RemoteReplica, ServeEngine, ServeServer
from lstm_tensorspark_tpu.serve.remote import RemoteBatcher
from lstm_tensorspark_tpu.serve.server import make_http_server
from lstm_tensorspark_tpu.serve.transport import (
    CircuitBreaker,
    PeerHTTPError,
    PeerTransport,
    SettledCache,
    TransportError,
)

_CFG = LMConfig(vocab_size=31, hidden_size=16, num_layers=1)


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(5), _CFG)


@pytest.fixture(scope="module")
def peer(params):
    """One live in-process peer serve host behind its HTTP endpoint,
    shared by the wire-level tests (each arms its own fault plane and
    disarms in finally)."""
    eng = ServeEngine(params, _CFG, rng_seed=0, num_slots=8,
                      prefill_buckets=(4, 8), batch_buckets=(1, 2, 4),
                      registry=MetricsRegistry())
    srv = ServeServer(eng, max_active=4, queue_size=16, window_ladder=(1, 4))
    httpd = make_http_server(srv, "127.0.0.1", 0)
    host, port = httpd.server_address[:2]
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    try:
        with srv:
            srv.warmup(prompt_lens=(8,))
            thread.start()
            yield srv, f"http://{host}:{port}"
    finally:
        httpd.shutdown()
        httpd.server_close()


def _post_generate(url, body, timeout=30.0):
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(body).encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode("utf-8") or "{}")


# ---- circuit breaker state machine --------------------------------------


def test_circuit_opens_after_consecutive_failures_only():
    cb = CircuitBreaker(open_after=3, rejoin_after=2)
    assert cb.state() == "closed" and cb.allow()
    cb.record_failure()
    cb.record_failure()
    assert cb.state() == "closed"          # below threshold
    cb.record_failure()
    assert cb.state() == "open" and not cb.allow()
    assert cb.opened_total == 1


def test_circuit_flap_damping_alternation_never_opens():
    """THE damping property: in the closed regime one success fully
    resets the failure streak, so an alternating lossy link can flap
    forever without opening the circuit."""
    cb = CircuitBreaker(open_after=2, rejoin_after=2)
    for _ in range(50):
        cb.record_failure()
        cb.record_success()
    assert cb.state() == "closed" and cb.opened_total == 0


def test_circuit_rejoin_needs_consecutive_successes():
    """One lucky probe through a flapping link must NOT rejoin: open →
    success moves to half_open; a failure resets; only rejoin_after
    consecutive successes close."""
    cb = CircuitBreaker(open_after=2, rejoin_after=2)
    cb.record_failure()
    cb.record_failure()
    assert cb.state() == "open"
    cb.record_success()
    assert cb.state() == "half_open" and not cb.allow()
    cb.record_failure()                     # flap mid-heal: back to open
    assert cb.state() == "open"
    cb.record_success()
    cb.record_success()
    assert cb.state() == "closed" and cb.allow()
    assert cb.closed_total == 1


def test_circuit_suspect_is_the_milder_damping_threshold():
    cb = CircuitBreaker(open_after=3, rejoin_after=2)
    assert not cb.suspect(2)
    cb.record_failure()
    cb.record_failure()
    assert cb.suspect(2)                    # damped before fully open
    assert cb.state() == "closed"
    cb.record_success()
    assert not cb.suspect(2)                # success resets the streak


def test_circuit_threshold_validation():
    with pytest.raises(ValueError):
        CircuitBreaker(open_after=0)
    with pytest.raises(ValueError):
        CircuitBreaker(rejoin_after=0)


# ---- settled cache (peer-side replay dedup) -----------------------------


def test_settled_cache_replay_hit_and_abandon():
    c = SettledCache()
    state, _ = c.begin("r1")
    assert state == "mine"
    c.settle("r1", 200, {"tokens": [1, 2]})
    state, hit = c.begin("r1")
    assert state == "hit" and hit == (200, {"tokens": [1, 2]})
    # abandoned ids re-execute: the next begin owns it again
    state, _ = c.begin("r2")
    assert state == "mine"
    c.abandon("r2")
    state, _ = c.begin("r2")
    assert state == "mine"
    assert c.stats()["hits"] == 1 and c.stats()["stores"] == 1


def test_settled_cache_concurrent_delivery_waits_for_the_first():
    c = SettledCache()
    assert c.begin("dup")[0] == "mine"
    got = {}

    def second_delivery():
        got["out"] = c.begin("dup", wait_timeout=5.0)

    t = threading.Thread(target=second_delivery)
    t.start()
    time.sleep(0.05)
    c.settle("dup", 200, {"tokens": [7]})
    t.join(timeout=5.0)
    assert got["out"] == ("hit", (200, {"tokens": [7]}))
    assert c.stats()["waits"] == 1


def test_settled_cache_lru_bound():
    c = SettledCache(max_entries=2)
    for i in range(4):
        rid = f"r{i}"
        c.begin(rid)
        c.settle(rid, 200, {"i": i})
    assert c.stats()["settled"] == 2
    assert c.begin("r0")[0] == "mine"       # evicted → re-executes
    assert c.begin("r3")[0] == "hit"        # newest survives


# ---- generate_timeout_s validation (satellite: the magic 120.0) ---------


def test_generate_timeout_validation():
    with pytest.raises(ValueError):
        RemoteBatcher("http://127.0.0.1:1", generate_timeout_s=-1.0)
    with pytest.raises(ValueError):
        RemoteReplica(1, "http://127.0.0.1:1", generate_timeout_s=-0.5)
    # 0 is the CLI convention for "no client-side bound"
    assert RemoteBatcher("http://127.0.0.1:1",
                         generate_timeout_s=0).generate_timeout_s is None
    assert RemoteBatcher("http://127.0.0.1:1",
                         generate_timeout_s=45.0).generate_timeout_s == 45.0


def test_transport_rejects_non_http_urls():
    with pytest.raises(ValueError):
        PeerTransport("https://example.com")


# ---- net fault grammar + hook semantics ---------------------------------


def test_net_fault_grammar():
    p = faults.FaultPlane("net_blackhole@1")
    assert p.net_blackhole == {1: None}     # until disarm (the heal)
    p = faults.FaultPlane("net_blackhole@1x2;net_flap@0x5;"
                          "net_latency@2x50;net_drop@3")
    assert p.net_blackhole == {1: 2}
    assert p.net_flap == {0: 5}
    assert p.net_latency_calls == {2: 50}
    assert p.net_drop_calls == {3}
    with pytest.raises(ValueError):
        faults.FaultPlane("net_drop@1x2")   # drop takes no xK window


def test_net_hook_blackhole_is_peer_scoped():
    p = faults.FaultPlane("net_blackhole@1")
    assert p.serve_net_hook(1, "heartbeat") == ("blackhole",)
    assert p.serve_net_hook(1, "generate") == ("blackhole",)
    assert p.serve_net_hook(0, "heartbeat") is None


def test_net_hook_flap_alternates_per_peer():
    p = faults.FaultPlane("net_flap@2x30")
    assert p.serve_net_hook(2, "heartbeat") == ("fail",)
    assert p.serve_net_hook(2, "heartbeat") is None
    assert p.serve_net_hook(2, "heartbeat") == ("fail",)
    assert p.serve_net_hook(1, "heartbeat") is None


def test_net_hook_latency_and_drop_count_generate_calls_only():
    p = faults.FaultPlane("net_latency@1x50;net_drop@2")
    # heartbeats never consume the generate-call counter
    assert p.serve_net_hook(0, "heartbeat") is None
    assert p.serve_net_hook(0, "generate") == ("latency", 50)
    assert p.serve_net_hook(0, "generate") == ("drop",)
    assert p.serve_net_hook(0, "generate") is None


# ---- wire-level: retries, fail-fast, flap damping, replay dedup ---------


def test_transport_retries_through_a_flapping_link(peer):
    _, url = peer
    transport = PeerTransport(url, peer=3, max_retries=2,
                              retry_base_s=0.01)
    faults.arm("net_flap@3x30")
    try:
        hb = transport.rpc_get("/replica/heartbeat", method="heartbeat")
        assert hb.get("status") in ("ok", "down")
        assert transport.retries_total == 1   # fail, backoff, ok
        assert transport.circuit.state() == "closed"
    finally:
        faults.disarm()
        transport.close()


def test_circuit_open_fails_fast_without_waiting_out_timeouts():
    faults.arm("net_blackhole@5")
    transport = PeerTransport("http://127.0.0.1:1", peer=5,
                              connect_timeout=0.2, max_retries=0,
                              circuit=CircuitBreaker(open_after=2))
    try:
        for _ in range(2):
            with pytest.raises(TransportError) as ei:
                transport.rpc_get("/replica/heartbeat", method="heartbeat",
                                  timeout=1.0, probe=True)
            assert ei.value.kind == "connect_timeout"
            assert ei.value.executed is False
        assert transport.circuit.is_open
        t0 = time.perf_counter()
        with pytest.raises(TransportError) as ei:
            transport.rpc_get("/replica/heartbeat", method="heartbeat",
                              timeout=1.0)
        assert ei.value.kind == "circuit_open"
        assert ei.value.executed is False     # never delivered: reroutable
        assert time.perf_counter() - t0 < 0.15, \
            "circuit-open must fail fast, not wait out a timeout"
    finally:
        faults.disarm()
        transport.close()


def test_flapping_heartbeat_below_threshold_never_retires(peer):
    """Satellite (c): an alternating ok/fail heartbeat link keeps the
    poller alive (retirement is refused-only), never opens the circuit
    (one success resets the streak), and heals cleanly after the flap
    window — the peer rejoins with NO restart of anything."""
    _, url = peer
    shim = RemoteBatcher(url, replica=1, poll_interval=0.05,
                         rpc_timeout=2.0)
    stop = threading.Event()
    poller = threading.Thread(target=shim.run, args=(stop,), daemon=True)
    faults.arm("net_flap@1x1")
    try:
        poller.start()
        time.sleep(1.2)                      # ride out the 1s flap window
        assert poller.is_alive(), \
            "flap failures must never retire the poller (refused-only)"
        assert shim.circuit.opened_total == 0
        assert shim.circuit.state() == "closed"
        assert not shim.suspect()
        # healed: heartbeats land again and the residency view is fresh
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            age = shim.heartbeat_age()
            if age is not None and age <= 3 * shim.poll_interval:
                break
            time.sleep(0.05)
        assert shim.heartbeat_age() is not None
        assert shim.heartbeat_age() <= 3 * shim.poll_interval
    finally:
        faults.disarm()
        stop.set()
        poller.join(timeout=5.0)


def test_duplicate_generate_post_decodes_exactly_once(peer):
    """Satellite (c): two deliveries of the same request_id produce ONE
    decode — identical tokens, the second marked ``replayed``, the
    settled-cache hit counted, and the peer's completed counter moves by
    exactly one."""
    srv, url = peer
    before = srv.stats()["batcher"]["completed"]
    hits_before = srv.settled.stats()["hits"]
    body = {"prompt": [1, 2, 3], "max_new_tokens": 4, "greedy": True,
            "timeout": 30.0, "request_id": "dup-once-1"}
    s1, r1 = _post_generate(url, body)
    s2, r2 = _post_generate(url, body)
    assert s1 == 200 and s2 == 200
    assert r1["tokens"] == r2["tokens"] and len(r1["tokens"]) == 4
    assert "replayed" not in r1 and r2["replayed"] is True
    assert srv.stats()["batcher"]["completed"] == before + 1
    assert srv.settled.stats()["hits"] == hits_before + 1


def test_dropped_response_replays_instead_of_double_decoding(peer):
    """End-to-end exactly-once over at-least-once delivery: net_drop
    loses the first response client-side (indeterminate), the transport
    retries under the request_id, and the peer serves the settled reply
    — one decode, one retry, tokens delivered."""
    srv, url = peer
    before = srv.stats()["batcher"]["completed"]
    transport = PeerTransport(url, peer=7, max_retries=2,
                              retry_base_s=0.01)
    faults.arm("net_drop@1")
    try:
        reply = transport.rpc_post(
            "/v1/generate",
            {"prompt": [2, 4], "max_new_tokens": 3, "greedy": True,
             "timeout": 30.0, "request_id": "drop-replay-1"},
            method="generate", timeout=30.0, replay_safe=True)
        assert len(reply["tokens"]) == 3
        assert reply.get("replayed") is True
        assert transport.retries_total == 1
        assert srv.stats()["batcher"]["completed"] == before + 1
    finally:
        faults.disarm()
        transport.close()
