"""Gradient accumulation: N microbatches must produce the full-batch update
exactly (equal microbatch sizes ⇒ mean-of-means == full mean), single-chip
and under DP; memory behavior is XLA's, but semantics are testable."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import make_dp_train_step, make_mesh, shard_batch
from lstm_tensorspark_tpu.parallel.data_parallel import replicate
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state


def _setup(B=8, T=12, V=23, H=16):
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    opt = make_optimizer("sgd", 0.5)

    def loss_fn(p, batch, rng):
        return lm_loss(p, batch, cfg)

    rng = np.random.RandomState(0)
    batch = {
        "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
        "targets": rng.randint(0, V, (B, T)).astype(np.int32),
    }
    return cfg, params, opt, loss_fn, batch


def test_accum_matches_full_batch_single_chip():
    cfg, params, opt, loss_fn, batch = _setup()
    s_full = init_train_state(params, opt, jax.random.PRNGKey(1))
    s_acc = init_train_state(params, opt, jax.random.PRNGKey(1))
    full = make_train_step(loss_fn, opt, jit=True)
    acc = make_train_step(loss_fn, opt, jit=True, grad_accum=4)
    s_full, m_full = full(s_full, batch)
    s_acc, m_acc = acc(s_acc, batch)
    np.testing.assert_allclose(
        float(m_full["loss"]), float(m_acc["loss"]), rtol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6),
        jax.device_get(s_full.params), jax.device_get(s_acc.params),
    )


def test_accum_matches_full_batch_dp():
    cfg, params, opt, loss_fn, batch = _setup(B=16)
    mesh = make_mesh(dp=4, devices=np.asarray(jax.devices()[:4]))
    full = make_dp_train_step(loss_fn, opt, mesh)
    acc = make_dp_train_step(loss_fn, opt, mesh, grad_accum=2)
    sb = shard_batch(batch, mesh)
    s0 = init_train_state(replicate(params, mesh), opt, jax.random.PRNGKey(1))
    s_full, m_full = full(s0, sb)
    s0 = init_train_state(replicate(params, mesh), opt, jax.random.PRNGKey(1))
    s_acc, m_acc = acc(s0, sb)
    np.testing.assert_allclose(
        float(m_full["loss"]), float(m_acc["loss"]), rtol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6),
        jax.device_get(s_full.params), jax.device_get(s_acc.params),
    )


def test_accum_multiple_steps_trains():
    """Loss decreases over a few accumulated steps (the path is trainable)."""
    cfg, params, opt, loss_fn, batch = _setup()
    step = make_train_step(loss_fn, opt, grad_accum=2)
    s = init_train_state(params, opt, jax.random.PRNGKey(1))
    losses = []
    for _ in range(8):
        s, m = step(s, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_cli_rejects_bad_accum(tmp_path):
    import pytest

    from lstm_tensorspark_tpu.cli import main

    with pytest.raises(SystemExit):
        main([
            "--dataset", "ptb_char", "--batch-size", "8", "--num-steps", "1",
            "--backend", "single", "--grad-accum", "3",  # 8 % 3 != 0
        ])
    with pytest.raises(SystemExit):
        main([
            "--dataset", "ptb_char", "--batch-size", "8", "--num-steps", "1",
            "--backend", "single", "--grad-accum", "2", "--stateful",
        ])


def test_cli_accum_end_to_end(tmp_path):
    import json

    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "m.jsonl"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "32", "--batch-size", "8",
        "--num-steps", "4", "--log-every", "2", "--grad-accum", "2",
        "--num-partitions", "2", "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert any("loss" in r for r in records)
