"""tools/readme_quality.py: the generated wall-clock-to-quality table —
measured rows render coherent summaries with vintage, invalidated rows
render honest pending cells from the banked CPU curve, and the committed
README is in sync with BASELINE_MEASURED.json."""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import readme_quality  # noqa: E402


def test_render_measured_and_pending_rows():
    results = {
        "config1_ptb_char": {
            "metric": "eval_ppl",
            "summary": {"target": 2.0, "tpu_seconds": 33.6,
                        "cpu_seconds": 53.5, "speedup": 1.59,
                        "speedup_train": 12.45, "speedup_warm": 8.25},
            "tpu_measured_at": "2026-08-01",
            "cpu_measured_at": "2026-07-31",
        },
        "config2_imdb": {
            "metric": "eval_accuracy",
            "invalidated": "task changed",
            "cpu": {"targets": {"0.55": {"t": 219.0}, "0.8": {"t": 1062.6}}},
            "cpu_measured_at": "2026-07-31",
        },
        # warm-only summary (only the warm legs share a common target):
        # legal output of bench_quality._summarize — must render, not crash
        "config3_wikitext2": {
            "metric": "eval_ppl",
            "summary": {"warm_target": 60.0, "speedup_warm": 78.28,
                        "tpu_seconds_warm": 8.3, "cpu_seconds_warm": 647.0},
        },
        # stale summary + invalidated marker: the marker wins — the
        # cross-task speedup must NOT render as a measured row
        "config4_uci": {
            "metric": "eval_mse",
            "invalidated": "task changed",
            "summary": {"target": 0.05, "tpu_seconds": 31.7,
                        "cpu_seconds": 148.9, "speedup": 4.7,
                        "speedup_train": 76.11},
        },
    }
    out = readme_quality.render(results)
    lines = out.splitlines()
    assert lines[0].startswith("| Config | Metric @ target | TPU | CPU |")
    row1 = next(l for l in lines if "PTB char" in l)
    assert "ppl ≤ 2" in row1 and "33.6 s" in row1 and "53.5 s" in row1
    assert "**12.4×**" in row1 and "8.2×" in row1
    # split vintages: both legs' dates appear when they differ
    assert "tpu 2026-08-01" in row1 and "cpu 2026-07-31" in row1
    row2 = next(l for l in lines if "IMDB" in l)
    assert "pending chip recovery" in row2
    # pending CPU cell uses the TIGHTEST reached target of the banked leg
    assert "1062.6 s to accuracy ≥ 0.8" in row2
    assert "banked 2026-07-31" in row2
    row3 = next(l for l in lines if "WikiText-2" in l)
    assert "ppl ≤ 60" in row3 and "— / — / 78.3×" in row3
    row4 = next(l for l in lines if "UCI" in l)
    assert "pending chip recovery" in row4 and "4.7×" not in row4
    # configs with no entry at all render a no-common-target row
    row5 = next(l for l in lines if "WT-103" in l)
    assert "no common target" in row5


def test_committed_readme_in_sync():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "tools/readme_quality.py", "--check"],
        capture_output=True, text=True, cwd=repo, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    # and the generator's source of truth parses
    json.load(open(os.path.join(repo, "BASELINE_MEASURED.json")))
