"""bench.py liveness gate: bounded retry window (VERDICT r3 item 1).

A transient wedge at bench start must not zero the round: the gate
re-probes until a probe succeeds or the window closes. These tests stub
the subprocess probe — the wedge itself obviously can't be simulated on
the CPU mesh — and check the retry/exhaustion control flow.
"""

import time

import pytest

import bench


class _FailJson(RuntimeError):
    """Stand-in for bench._fail_json's os._exit(LIVENESS_RC)."""


@pytest.fixture()
def fail_capture(monkeypatch):
    msgs = []

    def fake_fail(error):
        msgs.append(error)
        raise _FailJson(error)

    monkeypatch.setattr(bench, "_fail_json", fake_fail)
    return msgs


def test_retry_recovers_after_transient_failures(monkeypatch, fail_capture):
    calls = []

    def probe(timeout_s):
        calls.append(timeout_s)
        return None if len(calls) >= 3 else "probe matmul did not complete"

    monkeypatch.setattr(bench, "_probe_once", probe)
    monkeypatch.setattr(time, "sleep", lambda s: None)
    bench._liveness_probe(timeout_s=0.01, window_s=60.0)
    assert len(calls) == 3
    assert not fail_capture


def test_window_exhaustion_reports_attempts_and_last_error(
    monkeypatch, fail_capture
):
    def probe(timeout_s):
        return "probe exited rc=1"

    monkeypatch.setattr(bench, "_probe_once", probe)
    monkeypatch.setattr(time, "sleep", lambda s: None)
    with pytest.raises(_FailJson):
        bench._liveness_probe(timeout_s=0.01, window_s=0.05)
    (msg,) = fail_capture
    assert "probe exited rc=1" in msg
    assert "retry window" in msg


def test_zero_window_is_single_attempt(monkeypatch, fail_capture):
    calls = []

    def probe(timeout_s):
        calls.append(1)
        return "wedged"

    monkeypatch.setattr(bench, "_probe_once", probe)
    with pytest.raises(_FailJson):
        bench._liveness_probe(timeout_s=0.01, window_s=0.0)
    assert len(calls) == 1


def test_success_on_first_probe_skips_retry(monkeypatch, fail_capture):
    calls = []

    def probe(timeout_s):
        calls.append(1)
        return None

    monkeypatch.setattr(bench, "_probe_once", probe)
    bench._liveness_probe(timeout_s=0.01, window_s=60.0)
    assert len(calls) == 1
    assert not fail_capture


def test_fail_record_carries_last_good_evidence():
    """VERDICT r4 + resilience PR: a wedged round's failure line must embed
    the last complete measurement (value + provenance) from
    BENCH_TABLE.json while keeping value=0.0 honest, and exit with the
    DEDICATED liveness rc (resilience/exit_codes.py: 76 — no longer 3,
    which collided with chip_recovery's regression gate)."""
    import json
    import os
    import subprocess
    import sys as _sys

    from lstm_tensorspark_tpu.resilience.exit_codes import LIVENESS_RC

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [_sys.executable, "-c",
         "import bench, os\n"
         "os._exit = lambda c: (_ for _ in ()).throw(SystemExit(c))\n"
         "try:\n"
         "    bench._fail_json('wedge-test')\n"
         "except SystemExit as e:\n"
         "    print('EXIT_CODE=' + str(e.code))\n"],
        capture_output=True, text=True, timeout=120, cwd=repo,
    )
    lines = out.stdout.strip().splitlines()
    assert lines[-1] == f"EXIT_CODE={LIVENESS_RC}"  # dedicated liveness rc
    line = json.loads(lines[-2])
    assert line["value"] == 0.0  # honesty contract unchanged
    assert "wedge-test" in line["error"]
    lg = line["last_good"]
    assert lg["value"] > 0
    assert lg["unit"] == "seq/sec"
    if "commit" in lg:
        # git history available: value must come from THAT commit's blob
        committed = json.loads(subprocess.run(
            ["git", "show", f"{lg['commit']}:BENCH_TABLE.json"],
            capture_output=True, text=True, cwd=repo, timeout=30).stdout)
        assert lg["value"] == pytest.approx(committed["headline_seq_per_sec"])
        assert lg["captured_at"][:2] == "20"  # ISO date
    else:
        # degraded (no git): falls back to the on-disk table, no provenance
        table = json.load(open(os.path.join(repo, "BENCH_TABLE.json")))
        assert lg["value"] == pytest.approx(table["headline_seq_per_sec"])


def test_env_override_sets_window(monkeypatch, fail_capture):
    monkeypatch.setenv("LSTM_TSP_BENCH_LIVENESS_WINDOW_S", "0")
    calls = []

    def probe(timeout_s):
        calls.append(1)
        return "wedged"

    monkeypatch.setattr(bench, "_probe_once", probe)
    with pytest.raises(_FailJson):
        bench._liveness_probe(timeout_s=0.01)
    assert len(calls) == 1
