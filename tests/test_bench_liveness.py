"""bench.py liveness gate: bounded retry window (VERDICT r3 item 1).

A transient wedge at bench start must not zero the round: the gate
re-probes until a probe succeeds or the window closes. These tests stub
the subprocess probe — the wedge itself obviously can't be simulated on
the CPU mesh — and check the retry/exhaustion control flow.
"""

import time

import pytest

import bench


class _FailJson(RuntimeError):
    """Stand-in for bench._fail_json's os._exit(3)."""


@pytest.fixture()
def fail_capture(monkeypatch):
    msgs = []

    def fake_fail(error):
        msgs.append(error)
        raise _FailJson(error)

    monkeypatch.setattr(bench, "_fail_json", fake_fail)
    return msgs


def test_retry_recovers_after_transient_failures(monkeypatch, fail_capture):
    calls = []

    def probe(timeout_s):
        calls.append(timeout_s)
        return None if len(calls) >= 3 else "probe matmul did not complete"

    monkeypatch.setattr(bench, "_probe_once", probe)
    monkeypatch.setattr(time, "sleep", lambda s: None)
    bench._liveness_probe(timeout_s=0.01, window_s=60.0)
    assert len(calls) == 3
    assert not fail_capture


def test_window_exhaustion_reports_attempts_and_last_error(
    monkeypatch, fail_capture
):
    def probe(timeout_s):
        return "probe exited rc=1"

    monkeypatch.setattr(bench, "_probe_once", probe)
    monkeypatch.setattr(time, "sleep", lambda s: None)
    with pytest.raises(_FailJson):
        bench._liveness_probe(timeout_s=0.01, window_s=0.05)
    (msg,) = fail_capture
    assert "probe exited rc=1" in msg
    assert "retry window" in msg


def test_zero_window_is_single_attempt(monkeypatch, fail_capture):
    calls = []

    def probe(timeout_s):
        calls.append(1)
        return "wedged"

    monkeypatch.setattr(bench, "_probe_once", probe)
    with pytest.raises(_FailJson):
        bench._liveness_probe(timeout_s=0.01, window_s=0.0)
    assert len(calls) == 1


def test_success_on_first_probe_skips_retry(monkeypatch, fail_capture):
    calls = []

    def probe(timeout_s):
        calls.append(1)
        return None

    monkeypatch.setattr(bench, "_probe_once", probe)
    bench._liveness_probe(timeout_s=0.01, window_s=60.0)
    assert len(calls) == 1
    assert not fail_capture


def test_env_override_sets_window(monkeypatch, fail_capture):
    monkeypatch.setenv("LSTM_TSP_BENCH_LIVENESS_WINDOW_S", "0")
    calls = []

    def probe(timeout_s):
        calls.append(1)
        return "wedged"

    monkeypatch.setattr(bench, "_probe_once", probe)
    with pytest.raises(_FailJson):
        bench._liveness_probe(timeout_s=0.01)
    assert len(calls) == 1
