"""Generation tests: the jitted prefill+decode program (models/generate.py)
must exactly match a naive loop that re-runs the full training forward
(`lm_forward`) per token — proving the decode cell path cannot drift from the
train path — plus sampling-mode properties."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.models import (
    LMConfig,
    init_lm,
    lm_forward,
    make_generate_fn,
    sample_logits,
)


def _naive_greedy(params, prompt, cfg, n):
    """Oracle: full re-forward over the whole sequence for every new token."""
    toks = np.asarray(prompt)
    for _ in range(n):
        logits, _ = lm_forward(params, jnp.asarray(toks), cfg)
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1), np.int32)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return toks


def test_greedy_matches_full_reforward():
    cfg = LMConfig(vocab_size=37, hidden_size=24, num_layers=2)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    prompt = np.array([[3, 5, 7, 2], [11, 1, 4, 9]], np.int32)
    gen = make_generate_fn(cfg, max_new_tokens=12, greedy=True)
    out = np.asarray(gen(params, prompt, jax.random.PRNGKey(1)))
    oracle = _naive_greedy(params, prompt, cfg, 12)
    np.testing.assert_array_equal(out, oracle)


def test_greedy_tied_embeddings():
    cfg = LMConfig(vocab_size=19, hidden_size=16, num_layers=1, tie_embeddings=True)
    params = init_lm(jax.random.PRNGKey(2), cfg)
    prompt = np.array([[1, 2, 3]], np.int32)
    gen = make_generate_fn(cfg, max_new_tokens=6, greedy=True)
    out = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(out, _naive_greedy(params, prompt, cfg, 6))


def test_single_new_token():
    cfg = LMConfig(vocab_size=13, hidden_size=8)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    prompt = np.array([[4, 6]], np.int32)
    gen = make_generate_fn(cfg, max_new_tokens=1, greedy=True)
    out = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    assert out.shape == (1, 3)
    np.testing.assert_array_equal(out, _naive_greedy(params, prompt, cfg, 1))


def test_sampling_reproducible_and_in_range():
    cfg = LMConfig(vocab_size=29, hidden_size=16, num_layers=2)
    params = init_lm(jax.random.PRNGKey(3), cfg)
    prompt = np.array([[5, 8, 2]], np.int32)
    gen = make_generate_fn(cfg, max_new_tokens=20, temperature=0.8, top_k=5)
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(7)))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(7)))
    c = np.asarray(gen(params, prompt, jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(a, b)  # same key → same sample
    assert a.shape == (1, 23)
    assert (a >= 0).all() and (a < cfg.vocab_size).all()
    assert not np.array_equal(a, c)  # different key → (overwhelmingly) different


def test_top_k_restricts_support():
    """With top_k=1, sampling must equal greedy regardless of temperature."""
    cfg = LMConfig(vocab_size=17, hidden_size=12)
    params = init_lm(jax.random.PRNGKey(4), cfg)
    prompt = np.array([[2, 3, 4]], np.int32)
    g1 = make_generate_fn(cfg, max_new_tokens=8, top_k=1, temperature=2.0)
    g2 = make_generate_fn(cfg, max_new_tokens=8, greedy=True)
    out1 = np.asarray(g1(params, prompt, jax.random.PRNGKey(0)))
    out2 = np.asarray(g2(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(out1, out2)


def test_generate_ignores_remat_chunk():
    """remat is a training-memory device; generation must accept prompt
    lengths not divisible by the chunk (cfg override inside generate())."""
    cfg = LMConfig(vocab_size=19, hidden_size=16, remat_chunk=16)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    prompt = np.array([[1, 2, 3, 4, 5]], np.int32)  # T0=5, not % 16
    gen = make_generate_fn(cfg, max_new_tokens=4, greedy=True)
    out = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    assert out.shape == (1, 9)


def test_sample_logits_greedy_ignores_rng():
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 11).astype(np.float32))
    a = sample_logits(jax.random.PRNGKey(0), logits, greedy=True)
    b = sample_logits(jax.random.PRNGKey(99), logits, greedy=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(a), np.argmax(np.asarray(logits), -1))


def test_cli_generate_end_to_end(tmp_path):
    """CLI smoke: train a few steps then sample — prompt+continuation logged."""
    import json

    from lstm_tensorspark_tpu.cli import main

    jsonl = tmp_path / "m.jsonl"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "32", "--batch-size", "8",
        "--num-steps", "3", "--log-every", "1", "--backend", "single",
        "--compute-dtype", "float32",
        "--generate-tokens", "16", "--prompt", "four score", "--greedy",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    gen = [r for r in records if r.get("note") == "generate"]
    assert len(gen) == 1
    assert len(gen[0]["continuation"]) >= 16  # 16 chars (+ nothing dropped)


def test_top_p_one_equals_plain_sampling():
    cfg = LMConfig(vocab_size=21, hidden_size=12)
    params = init_lm(jax.random.PRNGKey(5), cfg)
    prompt = np.array([[1, 2]], np.int32)
    a = make_generate_fn(cfg, max_new_tokens=10, top_p=1.0)
    b = make_generate_fn(cfg, max_new_tokens=10)
    np.testing.assert_array_equal(
        np.asarray(a(params, prompt, jax.random.PRNGKey(3))),
        np.asarray(b(params, prompt, jax.random.PRNGKey(3))),
    )


def test_tiny_top_p_equals_greedy():
    """top_p→0 keeps only the argmax token regardless of temperature."""
    cfg = LMConfig(vocab_size=21, hidden_size=12)
    params = init_lm(jax.random.PRNGKey(5), cfg)
    prompt = np.array([[1, 2]], np.int32)
    a = make_generate_fn(cfg, max_new_tokens=10, top_p=1e-6, temperature=3.0)
    b = make_generate_fn(cfg, max_new_tokens=10, greedy=True)
    np.testing.assert_array_equal(
        np.asarray(a(params, prompt, jax.random.PRNGKey(3))),
        np.asarray(b(params, prompt, jax.random.PRNGKey(3))),
    )


def test_top_p_restricts_support():
    """With a peaked distribution, top_p sampling never emits tokens outside
    the nucleus."""
    logits = jnp.asarray([[10.0, 9.5, 0.0, -1.0, -2.0]] * 4)
    for key in range(20):
        toks = np.asarray(
            sample_logits(jax.random.PRNGKey(key), logits, top_p=0.9)
        )
        assert set(toks.tolist()) <= {0, 1}
