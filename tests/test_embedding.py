"""Parity tests for ops/embedding.py — the TPU-tuned vocabulary indexing.

The helpers promise: forward bit-identical to the gather formulation at
every vocab size, gradients equal up to float summation order (embedding)
or bit-exact (selected_logits: the one-hot backward scatters exactly one
term per position). A profile showed the gather/scatter formulations were
48% of the config-1 step on v5e; these tests pin that the fast forms are
drop-in numerically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lstm_tensorspark_tpu.ops.embedding import (
    _MM_GRAD_MAX_V,
    embed_lookup,
    selected_logits,
)


@pytest.mark.parametrize("V", [26, 370, _MM_GRAD_MAX_V, _MM_GRAD_MAX_V + 1])
def test_embed_lookup_forward_matches_take(V):
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    emb = jax.random.normal(k1, (V, 16), jnp.float32)
    toks = jax.random.randint(k2, (4, 9), 0, V, jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(embed_lookup(emb, toks)),
        np.asarray(jnp.take(emb, toks, axis=0)),
    )


@pytest.mark.parametrize("V", [26, _MM_GRAD_MAX_V + 1])
def test_embed_lookup_grad_matches_take(V):
    """Matmul-backward (small V) and scatter-backward (large V) agree with
    the plain take gradient; tight tolerance because the difference is
    summation order only."""
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    emb = jax.random.normal(k1, (V, 8), jnp.float32)
    toks = jax.random.randint(k2, (3, 17), 0, min(V, 26), jnp.int32)
    cot = jax.random.normal(k3, (3, 17, 8), jnp.float32)

    g_fast = jax.grad(lambda e: jnp.vdot(embed_lookup(e, toks), cot))(emb)
    g_ref = jax.grad(lambda e: jnp.vdot(jnp.take(e, toks, axis=0), cot))(emb)
    np.testing.assert_allclose(np.asarray(g_fast), np.asarray(g_ref),
                               rtol=1e-6, atol=1e-6)


def test_embed_lookup_repeated_tokens_accumulate():
    """Duplicate tokens must SUM their cotangents (the scatter-add
    semantics), not overwrite."""
    emb = jnp.zeros((4, 2), jnp.float32)
    toks = jnp.array([1, 1, 1], jnp.int32)
    g = jax.grad(lambda e: jnp.sum(embed_lookup(e, toks)))(emb)
    np.testing.assert_array_equal(np.asarray(g[1]), np.array([3.0, 3.0]))
    np.testing.assert_array_equal(np.asarray(g[0]), np.array([0.0, 0.0]))


@pytest.mark.parametrize("V", [26, _MM_GRAD_MAX_V + 1])
def test_selected_logits_forward_and_grad_exact(V):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    logits = jax.random.normal(k1, (5, 7, V), jnp.float32)
    tgt = jax.random.randint(k2, (5, 7), 0, V, jnp.int32)
    cot = jax.random.normal(k3, (5, 7), jnp.float32)

    ref = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
    np.testing.assert_array_equal(np.asarray(selected_logits(logits, tgt)),
                                  np.asarray(ref))

    g_fast = jax.grad(lambda l: jnp.vdot(selected_logits(l, tgt), cot))(logits)
    g_ref = jax.grad(
        lambda l: jnp.vdot(
            jnp.take_along_axis(l, tgt[..., None], axis=-1)[..., 0], cot
        )
    )(logits)
    np.testing.assert_array_equal(np.asarray(g_fast), np.asarray(g_ref))


def test_lm_loss_value_unchanged_by_fast_indexing():
    """lm_loss through the helpers equals the explicit gather formulation
    (the helpers are drop-in: one-hot sum has a single nonzero term)."""
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
    from lstm_tensorspark_tpu.models.lstm_lm import lm_forward

    cfg = LMConfig(vocab_size=26, hidden_size=16, num_layers=1)
    params = init_lm(jax.random.PRNGKey(3), cfg)
    k1, k2 = jax.random.split(jax.random.PRNGKey(4))
    toks = jax.random.randint(k1, (2, 12 + 1), 0, 26, jnp.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    loss, _ = lm_loss(params, batch, cfg)

    logits, _ = lm_forward(params, batch["inputs"], cfg)
    lg = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lg, axis=-1)
    tgt = jnp.take_along_axis(lg, batch["targets"][..., None], axis=-1)[..., 0]
    ref = jnp.mean(lse - tgt)
    np.testing.assert_array_equal(np.asarray(loss), np.asarray(ref))


@pytest.mark.parametrize("seed", range(6))
def test_embedding_helpers_property_sweep(seed):
    """Randomized shapes/vocab around the one-hot threshold: forward
    bit-equality and gradient agreement must hold for every draw."""
    rng = np.random.RandomState(seed)
    V = int(rng.choice([2, 26, 512, _MM_GRAD_MAX_V, _MM_GRAD_MAX_V + 1]))
    B = int(rng.randint(1, 5))
    T = int(rng.randint(1, 23))
    E = int(rng.choice([1, 8, 48]))
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    emb = jax.random.normal(k1, (V, E), jnp.float32)
    toks = jax.random.randint(k2, (B, T), 0, V, jnp.int32)
    cot = jax.random.normal(k3, (B, T, E), jnp.float32)

    np.testing.assert_array_equal(
        np.asarray(embed_lookup(emb, toks)),
        np.asarray(jnp.take(emb, toks, axis=0)))
    g_fast = jax.grad(lambda e: jnp.vdot(embed_lookup(e, toks), cot))(emb)
    g_ref = jax.grad(lambda e: jnp.vdot(jnp.take(e, toks, axis=0), cot))(emb)
    np.testing.assert_allclose(np.asarray(g_fast), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)

    logits = jax.random.normal(k1, (B, T, V), jnp.float32)
    ref = jnp.take_along_axis(logits, toks[..., None], axis=-1)[..., 0]
    np.testing.assert_array_equal(np.asarray(selected_logits(logits, toks)),
                                  np.asarray(ref))
