"""End-to-end serving tests: concurrent sessions through the in-process
client (and the real HTTP endpoint) must produce greedy output
token-identical to a direct `models/generate.py` call with the same
params/prompt — the ISSUE acceptance path — plus loadgen smoke.

One module-scoped server (started once, stopped at teardown) backs every
test except the deliberately-tiny backpressure stack and the CLI selftest
(which builds its own model through the real command path) — so the file
pays each XLA compile once."""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.serve import (
    InprocessClient,
    ServeEngine,
    ServeServer,
    run_loadgen,
)

_CFG = LMConfig(vocab_size=41, hidden_size=16, num_layers=2)
_N_NEW = 8
_PROMPTS = [
    np.array([7, 1], np.int32),
    np.array([3, 9, 2, 12, 30], np.int32),
]


@pytest.fixture(scope="module")
def stack():
    params = init_lm(jax.random.PRNGKey(7), _CFG)
    engine = ServeEngine(
        params, _CFG, num_slots=8,
        prefill_buckets=(4, 8), batch_buckets=(1, 2, 4),
    )
    server = ServeServer(engine, max_active=4, queue_size=16)
    server.start()
    yield params, server
    server.stop()


@pytest.fixture(scope="module")
def refs(stack):
    """Greedy references for _PROMPTS, one compiled program per prompt
    length, computed once for the whole file."""
    params, _ = stack
    gen = make_generate_fn(_CFG, max_new_tokens=_N_NEW, greedy=True)
    return [
        np.asarray(gen(params, p[None, :], jax.random.PRNGKey(0)))[0, p.size:]
        for p in _PROMPTS
    ]


def test_concurrent_inprocess_sessions_match_generate(stack, refs):
    _, server = stack
    client = InprocessClient(server)
    got = [None] * len(_PROMPTS)

    def run_one(i):
        got[i] = client.generate(_PROMPTS[i], max_new_tokens=_N_NEW)

    threads = [threading.Thread(target=run_one, args=(i,))
               for i in range(len(_PROMPTS))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for i in range(len(_PROMPTS)):
        np.testing.assert_array_equal(np.asarray(got[i], np.int32), refs[i])


def test_http_endpoint_roundtrip(stack, refs):
    from lstm_tensorspark_tpu.serve.server import make_http_server

    _, server = stack
    httpd = make_http_server(server, port=0)
    host, port = httpd.server_address[:2]
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    try:
        http_thread.start()
        base = f"http://{host}:{port}"
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
            # honest-health contract (test_serve_health.py): ok + the
            # scheduler heartbeat, not a constant smile
            assert health["ok"] is True and health["batcher_alive"] is True
        body = json.dumps({
            "prompt": _PROMPTS[1].tolist(), "max_new_tokens": _N_NEW,
            "greedy": True,
        }).encode()
        req = urllib.request.Request(
            base + "/v1/generate", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
        with urllib.request.urlopen(base + "/v1/stats", timeout=30) as r:
            stats = json.loads(r.read())
    finally:
        httpd.shutdown()
        httpd.server_close()
    np.testing.assert_array_equal(np.asarray(out["tokens"], np.int32), refs[1])
    assert stats["batcher"]["completed"] >= 1


def test_cli_serve_selftest():
    """The acceptance command: `cli serve --selftest` exits 0 (PASS)."""
    from lstm_tensorspark_tpu.cli import main

    rc = main([
        "serve", "--selftest", "--vocab-size", "31", "--hidden-units", "12",
        "--num-layers", "1", "--sessions", "2", "--max-new-tokens", "4",
        "--prefill-buckets", "8", "--batch-buckets", "2",
    ])
    assert rc == 0


def test_loadgen_reports_latency_and_throughput(stack):
    _, server = stack
    report = run_loadgen(
        server, vocab_size=_CFG.vocab_size, sessions=2,
        requests_per_session=2, prompt_len=4, max_new_tokens=4,
    )
    assert report["completed"] == 4 and report["rejected"] == 0
    assert report["failed"] == 0
    assert report["tokens_generated"] == 16
    for key in ("p50_latency_ms", "p99_latency_ms", "p50_ttft_ms",
                "tokens_per_sec"):
        assert report[key] > 0, (key, report)
    assert report["p99_latency_ms"] >= report["p50_latency_ms"]
    # inter-token latency is reported SEPARATELY from end-to-end latency
    # (the decode-window K tradeoff must be visible, not inferred): every
    # request contributes tokens-1 gaps, and a gap can't exceed the
    # request's own latency. ITL can be exactly 0.0 — a decode window's
    # K tokens arrive in one burst and share a timestamp — so assert
    # presence/ordering, not positivity.
    for key in ("p50_itl_ms", "p99_itl_ms", "max_itl_ms"):
        assert report[key] >= 0 and np.isfinite(report[key]), (key, report)
    assert report["p99_itl_ms"] >= report["p50_itl_ms"]
    assert report["max_itl_ms"] > 0
    assert report["max_itl_ms"] <= report["p99_latency_ms"]


def test_loadgen_open_loop_counts_backpressure():
    """Open-loop arrivals against a tiny queue: the run completes and every
    request is either completed or counted rejected (429-equivalent)."""
    params = init_lm(jax.random.PRNGKey(7), _CFG)
    engine = ServeEngine(params, _CFG, num_slots=2,
                         prefill_buckets=(4,), batch_buckets=(1,))
    server = ServeServer(engine, max_active=1, queue_size=1)
    with server:
        report = run_loadgen(
            server, vocab_size=_CFG.vocab_size, sessions=4,
            requests_per_session=2, prompt_len=3, max_new_tokens=3,
            mode="open", rate=200.0,
        )
    assert report["completed"] + report["rejected"] == 8
    assert report["completed"] >= 1
