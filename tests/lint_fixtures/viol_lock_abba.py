"""graftlint fixture: lock-order true positive — the classic 2-lock ABBA
(thread 1 runs transfer_out, thread 2 runs transfer_in, each holds its
first lock and blocks on the other's)."""

import threading


class Ledger:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.a = 0
        self.b = 0

    def transfer_out(self, n):
        with self._alock:
            with self._block:  # A -> B
                self.a -= n
                self.b += n

    def transfer_in(self, n):
        with self._block:
            with self._alock:  # B -> A: the ABBA cycle
                self.b -= n
                self.a += n
