"""graftlint fixture: clean twin of viol_wallclock — monotonic for
durations; the one legitimate wall-clock use (file-mtime comparison)
carries a suppression with its reason."""

import os
import time


def timed_call(fn):
    t0 = time.monotonic()
    out = fn()
    return out, time.monotonic() - t0


def is_stale(path, max_age_s):
    # wall clock on purpose: compared against st_mtime (wall-clock epoch)
    cutoff = time.time() - max_age_s  # graftlint: disable=wallclock-timing
    return os.stat(path).st_mtime < cutoff
