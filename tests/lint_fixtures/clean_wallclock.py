"""graftlint fixture: clean twin of viol_wallclock — monotonic for
durations; the one legitimate wall-clock use (file-mtime comparison)
carries a suppression with its reason; datetime.now() NOT used as a
duration (a human-facing record stamp) stays legal."""

import datetime
import os
import time


def timed_call(fn):
    t0 = time.monotonic()
    out = fn()
    return out, time.monotonic() - t0


def stamp_record(payload):
    # wall-clock for humans, never subtracted: not a duration
    return {"at": datetime.datetime.now().isoformat(), **payload}


def retention_cutoff(hours):
    # now() minus a timedelta is a wall-clock INSTANT (age gate), the
    # legitimate use — not a duration measurement
    return datetime.datetime.now() - datetime.timedelta(hours=hours)


def is_stale(path, max_age_s):
    # wall clock on purpose: compared against st_mtime (wall-clock epoch)
    cutoff = time.time() - max_age_s  # graftlint: disable=wallclock-timing
    return os.stat(path).st_mtime < cutoff
