"""graftlint fixture: swallowed-exception true positive — a catch-all
except: pass inside the scheduler hot loop, where a dropped failure has
no other surface (no metric, no log, no re-raise)."""


class Batcher:
    def __init__(self, engine):
        self.engine = engine
        self.queue = []

    def run(self, stop):
        while not stop.is_set():
            self.step()

    def step(self):
        if not self.queue:
            return
        req = self.queue.pop()
        try:
            self.engine.decode(req)
        except Exception:
            pass
