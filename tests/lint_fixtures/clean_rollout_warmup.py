"""graftlint fixture: clean twin of viol_rollout_warmup — warmup()
replays the decode program for EVERY resident model, so a request routed
to any resident (including one a rollout just added) can never be
charged a mid-traffic compile (the serve/engine.py multi-model
invariant: the rollout controller's warmup phase covers the full
per-model compile-key lattice before rejoin)."""


class MiniModelEngine:
    def __init__(self):
        self.residents = {"default": 0}
        self.compile_counts = {}
        self._fns = {}

    def model_fn(self, mid):
        count_key = ("model_decode", mid)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda toks: list(toks))

    def decode(self, toks, mid="default"):
        return self.model_fn(mid)(toks)

    def warmup(self, toks=(0,)):
        out = None
        for mid in self.residents:
            out = self.model_fn(mid)(toks)
        return out
