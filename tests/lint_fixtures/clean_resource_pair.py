"""graftlint fixture: clean twin of viol_resource_pair — every acquire
is released on every path (finally / with), and ownership transfers
(handle stored or returned) stay silent."""


class Spiller:
    def __init__(self, cache, disk):
        self.cache = cache
        self.disk = disk
        self._in_flight = 0
        self._held = {}

    def snapshot(self, sid):
        self.cache.pin(sid)
        try:
            return self.disk.read(sid)
        finally:
            self.cache.unpin(sid)

    def flush_one(self, sid, state):
        self._in_flight += 1
        try:
            self.disk.write(sid, state)
        finally:
            self._in_flight -= 1

    def adopt(self, sid):
        # ownership transfer: the pin outlives this frame by design —
        # the key is stored on the instance, so the site goes silent
        self.cache.pin(sid)
        self._held[sid] = True


def read_config(path):
    with open(path) as f:  # the with form manages the handle
        return f.read()


def append_line(path, line):
    f = open(path, "a")
    try:
        f.write(line)
    finally:
        f.close()
