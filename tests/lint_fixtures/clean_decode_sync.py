"""graftlint fixture: clean twin of viol_decode_sync — the scheduler
reads the decode window's token block + on-device summary ONLY through
the designated fetch_window_summary point (allow-listed alongside
fetch_window), so the one-sync-per-window contract survives the Pallas
kernel's extra summary arrays."""

import numpy as np


class Batcher:
    def __init__(self, engine):
        self.engine = engine
        self.pending = None

    def run(self, stop):
        while not stop.is_set():
            self.step()

    def step(self):
        if self.pending is None:
            return
        win = self.pending
        self.pending = None
        # the designated readback — both the plain call and an
        # np.asarray wrapped around it are blessed
        toks = np.asarray(self.engine.fetch_window_summary(win)[0])
        self.engine.distribute(toks)
