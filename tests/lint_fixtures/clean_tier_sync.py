"""graftlint fixture: clean twin of viol_tier_sync — the spill worker
fetches ONLY through the designated fetch_detached point (allow-listed
like the batcher's fetch_window), so the rule covers the thread without
baselining it."""

import numpy as np


class SessionTiers:
    def __init__(self, cache):
        self.cache = cache
        self.queue = []

    def run(self, stop):
        while not stop.is_set():
            self.step()

    def step(self):
        if not self.queue:
            return
        sid, h, c = self.queue.pop()
        # the designated device→host fetch of the spill plane — both the
        # plain call and an np.asarray wrapped around it are blessed
        state = np.asarray(self.cache.fetch_detached(h, c))
        self.cache.store(sid, state)
