"""graftlint fixture: clean twin of viol_warmup_train — warmup()
dispatches every ``("train_step", bucket, bptt_mode)`` program in the
lattice, so no training-step executable compiles inside a timed
sample."""


class MiniStepCache:
    def __init__(self):
        self.compile_counts = {}
        self._fns = {}

    def step_fn(self, bucket, bptt_mode):
        count_key = ("train_step", bucket, bptt_mode)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda s, b: (s, b))

    def run(self, state, batch, bucket, bptt_mode):
        return self.step_fn(bucket, bptt_mode)(state, batch)

    def warmup(self, state, batch, buckets=((1, 8),),
               modes=("sequential", "assoc")):
        out = None
        for bucket in buckets:
            for mode in modes:
                out = self.step_fn(bucket, mode)(state, batch)
        return out
