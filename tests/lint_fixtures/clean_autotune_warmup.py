"""graftlint fixture: clean twin of viol_autotune_warmup — warmup()
dispatches the window program for EVERY ladder rung the controller's
knob may cap to, so no knob move can ever charge a live request a
mid-traffic compile (the serve/autotune.py no-compile invariant:
set_window_cap / set_prefill_chunk only accept warmed values)."""


class MiniKnobEngine:
    def __init__(self, ladder=(1, 4, 8)):
        self.ladder = ladder
        self.window_cap = ladder[-1]
        self.compile_counts = {}
        self._fns = {}

    def window_fn(self, k):
        count_key = ("knob_window", k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda toks: toks[:k])

    def decode(self, toks):
        return self.window_fn(self.window_cap)(toks)

    def warmup(self, toks=(0,)):
        out = None
        for k in self.ladder:
            out = self.window_fn(k)(toks)
        return out
