"""graftlint fixture: clean twin of viol_autotune — the controller
thread parks on a stop Event its loop waits on, and stop() both sets the
flag and joins the stored handle (the serve/autotune.py lifecycle
contract: ServeServer.stop() drives AutoTuner.stop())."""

import threading


class MiniTuner:
    def __init__(self, server):
        self.server = server
        self._stop = threading.Event()
        self._thread = None
        self.ticks = 0

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mini-autotuner", daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(0.25):
            self.tick()

    def tick(self):
        self.ticks += 1

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
