"""graftlint fixture: clean twin of viol_thread_lifecycle — one worker
parked by a close() flag its loop reads, one joined by stop(), and a
non-daemon writer the interpreter joins at exit (out of scope)."""

import threading


class Poller:
    def __init__(self):
        self._thread = None
        self._queue = []
        self._closed = False

    def ensure_worker(self):
        self._closed = False
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self.run, name="poller", daemon=True)
            self._thread.start()

    def run(self):
        while not self._closed:
            if self._queue:
                self._queue.pop()

    def close(self):
        self._closed = True


class Scheduler:
    def __init__(self):
        self.thread = None
        self._stop = threading.Event()

    def start(self):
        self.thread = threading.Thread(
            target=self._loop, daemon=True)
        self.thread.start()

    def _loop(self):
        while not self._stop.is_set():
            pass

    def stop(self):
        self._stop.set()
        self.thread.join(timeout=5.0)


class Writer:
    def __init__(self):
        self._thread = None

    def save(self, payload):
        # non-daemon: the interpreter joins it at exit — out of scope
        self._thread = threading.Thread(target=self._write,
                                        args=(payload,))
        self._thread.start()

    def _write(self, payload):
        del payload
