"""graftlint fixture: clean twin of viol_lock_abba — both paths acquire
in the same global order (A before B), so the acquisition graph is
acyclic."""

import threading


class Ledger:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.a = 0
        self.b = 0

    def transfer_out(self, n):
        with self._alock:
            with self._block:  # A -> B
                self.a -= n
                self.b += n

    def transfer_in(self, n):
        with self._alock:
            with self._block:  # A -> B again: consistent order
                self.b -= n
                self.a += n
