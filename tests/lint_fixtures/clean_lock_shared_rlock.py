"""graftlint fixture: the shared-RLock pattern that must NOT fire.

Exactly the PrefixCache/StateCache arrangement: the overlay shares the
base cache's reentrant lock (``self._lock = cache._lock``), so the
listener fires under the very lock the overlay's own methods take, and
overlay methods re-enter base methods under it. Reentrant re-acquisition
of ONE merged lock is the sanctioned design, not an ABBA."""

import threading


class BaseCache:
    def __init__(self):
        self._lock = threading.RLock()
        self._slots = {}
        self.evict_listeners = []

    def acquire(self, sid):
        with self._lock:
            slot = self._slots.setdefault(sid, len(self._slots))
            return slot

    def evict(self, sid):
        with self._lock:
            self._slots.pop(sid, None)
            for listener in self.evict_listeners:
                listener(sid)


class Overlay:
    def __init__(self, cache: BaseCache):
        self.cache = cache
        self._lock = cache._lock  # shared on purpose (see module doc)
        self._entries = {}
        cache.evict_listeners.append(self._on_evicted_locked)

    def lookup(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self.cache.acquire(key)  # reentrant: same merged lock
            return entry

    def _on_evicted_locked(self, sid):
        self._entries.pop(sid, None)
