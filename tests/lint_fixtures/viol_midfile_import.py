"""graftlint fixture: mid-file-import true positive — a module-level
import stranded after the first definition (the PR 4 train/loop.py
class)."""

import sys


def early():
    return sys.maxsize


import os  # stranded: hoist to the header


def late(path):
    return os.path.basename(path)
