"""graftlint fixture: metrics-consistency true positives — one name
registered as two kinds, one registered with two labelsets, and a
.labels() call whose keys don't match the registration."""


def record_queue(reg, depth):
    m = reg.gauge("fix_queue_depth", "requests waiting")
    m.set(depth)


def count_queue(reg):
    # same name, different kind: dashboards can't average a counter
    m = reg.counter("fix_queue_depth", "requests waiting")
    m.inc()


def outcomes_a(reg):
    fam = reg.counter("fix_requests_total", "requests by outcome",
                      labelnames=("outcome",))
    fam.labels(outcome="ok").inc()


def outcomes_b(reg):
    # same name, different labelset
    fam = reg.counter("fix_requests_total", "requests by outcome",
                      labelnames=("status",))
    fam.labels(status="ok").inc()


def windows(reg, k):
    fam = reg.counter("fix_windows_total", "windows by size",
                      labelnames=("k",))
    fam.labels(size=str(k)).inc()  # wrong label key at the call site
