"""graftlint fixture: lock-order true positive — a 3-lock cycle routed
through a listener callback (the PrefixCache.evict_listeners shape):

    Cache._lock   --(evict fires listeners)-->  Index._lock
    Index._lock   --(refresh calls store)-->    Store._lock
    Store._lock   --(flush calls cache)-->      Cache._lock

No single method nests all three; only the callback edge closes the
cycle — exactly the hazard a reviewer reading one class at a time
cannot see."""

import threading


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}
        self.evict_listeners = []

    def evict(self, sid):
        with self._lock:
            self._slots.pop(sid, None)
            for listener in self.evict_listeners:
                listener(sid)


class Index:
    def __init__(self, cache: Cache, store: "Store"):
        self._lock = threading.Lock()
        self._entries = {}
        self.store = store
        cache.evict_listeners.append(self._on_evicted)

    def _on_evicted(self, sid):
        with self._lock:
            self._entries.pop(sid, None)
            self.store.refresh(sid)


class Store:
    def __init__(self, cache: Cache):
        self._lock = threading.Lock()
        self.cache = cache

    def refresh(self, sid):
        with self._lock:
            self.flush(sid)

    def flush(self, sid):
        self.cache.evict(sid)
