"""graftlint fixture: host-sync true positive on the Pallas decode-window
readback path — the scheduler closure fetches the window's on-device
summary with a bare jax.device_get instead of going through the
designated fetch_window_summary point."""

import jax


class Batcher:
    def __init__(self, engine):
        self.engine = engine
        self.pending = None

    def run(self, stop):
        while not stop.is_set():
            self.step()

    def step(self):
        if self.pending is None:
            return
        win = self.pending
        self.pending = None
        # stray sync: the summary must come through fetch_window_summary
        toks, rem, alive = jax.device_get(
            (win.tokens, win.remaining, win.alive))
        self.engine.distribute(toks, rem, alive)
