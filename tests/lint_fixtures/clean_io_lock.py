"""graftlint fixture: clean twin of viol_io_lock — the lock hold only
snapshots in-memory state; reads, writes and the device fetch all run
outside it. The metadata probe (os.path.exists) under the lock is the
sanctioned deduped-residency-stat pattern and must NOT fire."""

import os
import threading


class StateCache:
    def __init__(self, directory):
        self.directory = directory
        self._lock = threading.Lock()
        self._index = {}

    def _path(self, sid):
        return os.path.join(self.directory, sid)

    def fill(self, sid):
        with self._lock:
            path = self._index.get(sid)
        if path is None:
            return None
        with open(path, "rb") as f:  # IO outside the lock hold
            return f.read()

    def has(self, sid):
        with self._lock:
            # metadata probe: bounded, sanctioned under the hot lock
            # (the router's deduped disk-residency stat)
            return sid in self._index or os.path.exists(self._path(sid))

    def store(self, sid, data):
        path = self._path(sid)
        with open(path, "wb") as f:
            f.write(data)
        with self._lock:
            self._index[sid] = path
