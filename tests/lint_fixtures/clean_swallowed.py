"""graftlint fixture: clean twin of viol_swallowed — scheduler-side
failures either count a metric or are caught NARROWLY (expected-absence
handling around a list remove stays legal), and catch-all-pass outside
the scheduler closure is out of scope."""


class Batcher:
    def __init__(self, engine):
        self.engine = engine
        self.queue = []
        self.failed = 0

    def run(self, stop):
        while not stop.is_set():
            self.step()

    def step(self):
        if not self.queue:
            return
        req = self.queue.pop()
        try:
            self.engine.decode(req)
        except Exception:
            self.failed += 1  # counted: the failure has a surface
        try:
            self.queue.remove(req)
        except ValueError:
            pass  # narrow type documents the expected absence

    def stats(self):
        # not in the run/step/drain closure: client-side best-effort
        # cleanup may stay silent
        try:
            return {"queued": len(self.queue), "failed": self.failed}
        except Exception:
            pass
        return {}
