"""graftlint fixture: clean twin of viol_host_sync — same shapes, no
stray syncs. The scheduler fetches ONLY through the designated
fetch_window point; traced bodies stay on device; host-side np.asarray
outside hot scopes is fine."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def make_step(params):
    def step_fn(x):
        return jnp.dot(params, x)  # stays on device

    return jax.jit(step_fn)


def scan_all(xs, carry):
    def body(c, x):
        c = c + x
        return c, c

    return lax.scan(body, carry, xs)


def pack_prompt(prompt):
    # not a hot scope: plain host-side packing may use numpy freely
    return np.asarray(prompt, np.int32)


class Batcher:
    def __init__(self, engine):
        self.engine = engine
        self.pending = None

    def step(self):
        win = self.engine.dispatch()
        # the designated sync point of the windowed path
        return np.asarray(self.engine.fetch_window(win))

    def run(self, stop):
        while not stop.is_set():
            self.step()
