"""graftlint fixture: resource-pairing true positives — a pinned slot
leaked on an exception path (the PR 7 leaked-pin class) and an in-flight
counter whose decrement a raising disk write skips (the PR 8
wedged-flush class: flush() waits on a count nobody will ever drop)."""


class Spiller:
    def __init__(self, cache, disk):
        self.cache = cache
        self.disk = disk
        self._in_flight = 0

    def snapshot(self, sid):
        self.cache.pin(sid)
        state = self.disk.read(sid)  # may raise: the pin leaks
        self.cache.unpin(sid)
        return state

    def flush_one(self, sid, state):
        self._in_flight += 1
        self.disk.write(sid, state)  # may raise: the counter wedges
        self._in_flight -= 1
