"""graftlint fixture: clean twin of viol_remote_sync — the heartbeat
poller does the HTTP GET on its own thread OUTSIDE the lock and
publishes an in-memory residency snapshot; the affinity probe answers
from that snapshot under the lock with zero network."""

import json
import threading
import urllib.request


class PeerTransport:
    def __init__(self, url):
        self.url = url

    def rpc_get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=5.0) as resp:
            return json.loads(resp.read().decode("utf-8"))


class Router:
    def __init__(self, transport: PeerTransport):
        self.transport = transport
        self._lock = threading.Lock()
        self._residency = frozenset()

    def poll(self):
        # network outside any lock hold (the heartbeat poller thread)
        hb = self.transport.rpc_get("/replica/heartbeat")
        ids = frozenset(hb.get("session_ids", ()))
        with self._lock:
            self._residency = ids

    def has_session(self, sid):
        with self._lock:
            # pure in-memory membership — never blocks on a peer
            return sid in self._residency
