"""graftlint fixture: warmup-coverage true positive for the speculative
verify-window family — the engine grows a ("spec_window", ...) compile
family next to the plain decode window's, but warmup() only dispatches
the plain path: the first speculative step after `--speculative` boots
pays the joint draft+verify program's XLA compile mid-traffic, exactly
the latency spike speculation exists to avoid."""


class MiniEngine:
    def __init__(self, speculative=False, spec_ladder=(2, 4)):
        self.speculative = speculative
        self.spec_ladder = spec_ladder
        self.compile_counts = {}
        self._fns = {}

    def _get_window_fn(self, bucket, k):
        count_key = ("decode_window", bucket, k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def _get_spec_window_fn(self, bucket, k_draft):
        count_key = ("spec_window", bucket, k_draft)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def decode_window(self, tokens, k):
        if self.speculative and k in self.spec_ladder:
            return self._get_spec_window_fn(len(tokens), k)(tokens)
        return self._get_window_fn(len(tokens), k)(tokens)

    def warmup(self):
        # only the plain family: a speculative engine compiles its
        # verify windows mid-traffic on the first drafted step
        return self._get_window_fn(1, 4)([0])
