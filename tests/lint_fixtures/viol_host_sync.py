"""graftlint fixture: host-sync-in-hot-path true positives ONLY.

Three hot scopes, one stray sync each: a jit-traced body, a lax.scan
body, and a scheduler (Batcher) hot-loop method."""

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def make_step(params):
    def step_fn(x):
        y = jnp.dot(params, x)
        return np.asarray(y)  # sync inside a traced body

    return jax.jit(step_fn)


def scan_all(xs, carry):
    def body(c, x):
        c = c + x
        bad = c.item()  # sync inside the scan body
        return c, bad

    return lax.scan(body, carry, xs)


class Batcher:
    def __init__(self, engine):
        self.engine = engine
        self.pending = None

    def step(self):
        win = self.engine.dispatch()
        toks = jax.device_get(win.tokens)  # stray sync in the hot loop
        return toks

    def run(self, stop):
        while not stop.is_set():
            self.step()
