"""graftlint fixture: clean twin of viol_metrics — every name has one
kind, one labelset, matching .labels() keys; a help-less re-fetch of an
existing family (the registry's idempotent-lookup idiom) is fine, as is
re-binding the family variable between registrations."""


def record_queue(reg, depth):
    m = reg.gauge("fix_queue_depth", "requests waiting")
    m.set(depth)


def scrape_queue(reg):
    return reg.gauge("fix_queue_depth").value  # idempotent re-fetch


def outcomes(reg):
    fam = reg.counter("fix_requests_total", "requests by outcome",
                      labelnames=("outcome",))
    fam.labels(outcome="ok").inc()
    fam.labels(outcome="failed").inc()
    # re-bind to a second family: labels() below resolves to THIS one
    fam = reg.counter("fix_windows_total", "windows by size",
                      labelnames=("k",))
    fam.labels(k="4").inc()
