"""graftlint fixture: warmup-coverage true positive for a SECOND window
kernel family — the engine grows a ("decode_window_pallas", ...) compile
family next to the scan window's, but warmup() only dispatches the scan
path: the first pallas-served request pays the kernel's XLA compile
mid-traffic."""


class MiniEngine:
    def __init__(self, decode_kernel="scan"):
        self.decode_kernel = decode_kernel
        self.compile_counts = {}
        self._fns = {}

    def _get_window_fn(self, bucket, k):
        count_key = ("decode_window", bucket, k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def _get_window_pallas_fn(self, bucket, k):
        count_key = ("decode_window_pallas", bucket, k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def decode_window(self, tokens, k):
        if self.decode_kernel == "pallas":
            return self._get_window_pallas_fn(len(tokens), k)(tokens)
        return self._get_window_fn(len(tokens), k)(tokens)

    def warmup(self):
        # only the scan family: a pallas engine compiles mid-traffic
        return self._get_window_fn(1, 4)([0])
