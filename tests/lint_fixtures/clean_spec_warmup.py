"""graftlint fixture: clean twin of viol_spec_warmup — warmup() reaches
the window dispatcher that covers BOTH the plain decode family and the
("spec_window", ...) verify family over every spec-ladder rung, so a
`--speculative` boot has its joint draft+verify programs compiled
before the first drafted step."""


class MiniEngine:
    def __init__(self, speculative=False, spec_ladder=(2, 4)):
        self.speculative = speculative
        self.spec_ladder = spec_ladder
        self.compile_counts = {}
        self._fns = {}

    def _get_window_fn(self, bucket, k):
        count_key = ("decode_window", bucket, k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def _get_spec_window_fn(self, bucket, k_draft):
        count_key = ("spec_window", bucket, k_draft)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def decode_window(self, tokens, k):
        if self.speculative and k in self.spec_ladder:
            return self._get_spec_window_fn(len(tokens), k)(tokens)
        return self._get_window_fn(len(tokens), k)(tokens)

    def warmup(self):
        # warms through the dispatcher at every ladder rung plus the
        # plain window: every family a real dispatch can reach is
        # reachable from here, speculative or not
        out = self.decode_window([0], 1)
        for k in self.spec_ladder:
            out = self.decode_window([0], k)
        return out
