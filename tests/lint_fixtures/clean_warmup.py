"""graftlint fixture: clean twin of viol_warmup — warmup() reaches every
compile-key family (beam included), so no program compiles
mid-traffic."""


class MiniEngine:
    def __init__(self):
        self.compile_counts = {}
        self._fns = {}

    def _get_decode_fn(self, bucket):
        count_key = ("decode", bucket)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def _get_beam_fn(self, bucket, width):
        count_key = ("decode_beam", bucket, width)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def decode(self, tokens):
        return self._get_decode_fn(len(tokens))(tokens)

    def decode_beam(self, tokens, width):
        return self._get_beam_fn(len(tokens), width)(tokens)

    def warmup(self, widths=(1, 4)):
        out = self.decode([0])
        for w in widths:
            self.decode_beam([0], w)
        return out
