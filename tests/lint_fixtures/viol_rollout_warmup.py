"""graftlint fixture: warmup-coverage true positive for the PER-MODEL
namespace shape — a multi-model engine whose ``("model_decode", mid)``
compile-key family is only reachable through live dispatch, never from
``warmup()``: the first request routed to a freshly-added resident
charges a live request the mid-traffic XLA compile the rollout
controller's warmup phase exists to absorb (the PR 16 contract: every
RESIDENT model's program lattice is replayed off-path before the
replica rejoins rotation)."""


class MiniModelEngine:
    def __init__(self):
        self.residents = {"default": 0}
        self.compile_counts = {}
        self._fns = {}

    def model_fn(self, mid):
        count_key = ("model_decode", mid)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda toks: list(toks))

    def decode(self, toks, mid="default"):
        return self.model_fn(mid)(toks)

    def warmup(self):
        # never dispatches model_fn: every resident a request can route
        # to compiles mid-traffic on first touch
        return None
