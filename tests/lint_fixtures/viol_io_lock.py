"""graftlint fixture: io-under-lock true positives — a blocking file
read directly inside the shared cache lock, and disk IO reached through
a resolvable callee while the router's global lock is held (the class
PR 8's review rounds fixed three times)."""

import os
import threading


class StateCache:
    def __init__(self, directory):
        self.directory = directory
        self._lock = threading.Lock()
        self._index = {}

    def fill(self, sid):
        with self._lock:
            path = os.path.join(self.directory, sid)
            with open(path, "rb") as f:  # blocking read under the hot lock
                data = f.read()
            self._index[sid] = len(data)
            return data


class Store:
    def __init__(self, directory):
        self.directory = directory

    def persist(self, sid):
        src = os.path.join(self.directory, sid + ".tmp")
        os.replace(src, os.path.join(self.directory, sid))


class Router:
    def __init__(self, store: Store):
        self.store = store
        self._lock = threading.Lock()

    def retire(self, sid):
        with self._lock:
            # the callee resolves, and IT does the disk IO — the fsync
            # still runs under the global admission lock
            self.store.persist(sid)
