"""graftlint fixture: clean twin of viol_rollout — the rollout
controller's worker thread parks on a stop Event its loop waits on, and
stop() both sets the flag and joins the stored handle (the
serve/rollout.py lifecycle contract: ServeServer.stop() drives
RolloutController.stop() BEFORE stopping the replicas the controller
might be mid-drain on)."""

import threading


class MiniRollout:
    def __init__(self, server):
        self.server = server
        self._queue = []
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mini-rollout", daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(0.25):
            if self._queue:
                self.roll(self._queue.pop(0))

    def roll(self, move):
        return move

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
