"""graftlint fixture: host-sync true positive in the TIER SPILL WORKER
scope — a SessionTiers-named class whose run() closure performs a bare
device→host fetch instead of going through the designated
fetch_detached point."""

import numpy as np


class SessionTiers:
    def __init__(self, cache):
        self.cache = cache
        self.queue = []

    def run(self, stop):
        while not stop.is_set():
            self.step()

    def step(self):
        if not self.queue:
            return
        sid, h, c = self.queue.pop()
        # stray sync in the spill worker: must go through fetch_detached
        state = (np.asarray(h), np.asarray(c))
        self.cache.store(sid, state)
