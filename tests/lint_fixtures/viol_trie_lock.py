"""graftlint fixture: lock-order true positive — a prefix TRIE overlay
that takes its OWN lock while serving the state cache's eviction
listener (the PrefixTrie shape done wrong):

    SlotCache._lock  --(evict fires listeners)-->  Trie._lock
    Trie._lock       --(lookup pins the slot)-->   SlotCache._lock

Each class looks locally consistent; only the listener edge closes the
ABBA cycle. The sanctioned design shares the cache's reentrant lock
(see clean_trie_lock.py) — a private trie lock deadlocks the first
time an eviction races a lookup."""

import threading


class SlotCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}
        self._pinned = set()
        self.evict_listeners = []

    def pin(self, sid):
        with self._lock:
            self._pinned.add(sid)

    def evict(self, sid):
        with self._lock:
            slot = self._slots.pop(sid, None)
            for listener in self.evict_listeners:
                listener(sid, slot)


class Trie:
    def __init__(self, cache: SlotCache):
        self.cache = cache
        self._lock = threading.Lock()  # PRIVATE lock: the hazard
        self._nodes = {}
        cache.evict_listeners.append(self._on_slot_evicted)

    def lookup(self, key):
        with self._lock:
            node = self._nodes.get(key)
            if node is not None:
                self.cache.pin(node["sid"])  # Trie -> SlotCache edge
            return node

    def _on_slot_evicted(self, sid, slot):
        with self._lock:  # SlotCache -> Trie edge: closes the cycle
            self._nodes.pop(sid, None)
