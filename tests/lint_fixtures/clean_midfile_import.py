"""graftlint fixture: clean twin of viol_midfile_import — every
sanctioned import-section shape at once: __future__, plain imports, the
try/except shim, a guarded sys.path bootstrap, and post-bootstrap
imports. Function-level lazy imports stay legal."""

from __future__ import annotations

import os
import sys

try:  # the jax >= 0.4.35 shim shape
    from json import loads
except ImportError:  # pragma: no cover
    loads = None

_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)

import json  # still the import section: only bootstrap preceded it


def lazy_user():
    import base64  # lazy by design: legal

    return base64, json, loads
