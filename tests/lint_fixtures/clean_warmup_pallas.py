"""graftlint fixture: clean twin of viol_warmup_pallas — warmup()
reaches the window dispatcher that covers BOTH kernel families (scan and
pallas), so whichever kernel the engine resolved to, its window programs
are compiled before traffic."""


class MiniEngine:
    def __init__(self, decode_kernel="scan"):
        self.decode_kernel = decode_kernel
        self.compile_counts = {}
        self._fns = {}

    def _get_window_fn(self, bucket, k):
        count_key = ("decode_window", bucket, k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def _get_window_pallas_fn(self, bucket, k):
        count_key = ("decode_window_pallas", bucket, k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def decode_window(self, tokens, k):
        if self.decode_kernel == "pallas":
            return self._get_window_pallas_fn(len(tokens), k)(tokens)
        return self._get_window_fn(len(tokens), k)(tokens)

    def warmup(self, ks=(1, 4)):
        # warms through the dispatcher: every family a real dispatch can
        # reach is reachable from here, whichever kernel is resolved
        out = None
        for k in ks:
            out = self.decode_window([0], k)
        return out
