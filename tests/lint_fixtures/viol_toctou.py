"""graftlint fixture: toctou-fs true positives — exists()-guarded
remove and open on the same path expression (the sidecar class PR 8
round 3 converted to try/remove: the file can vanish between the two
calls)."""

import os


def drop_sidecar(path):
    side = path + ".sha256"
    if os.path.exists(side):
        os.remove(side)  # another writer can unlink it first


def read_meta(path):
    if os.path.exists(path):
        with open(path) as f:
            return f.read()
    return None
