"""graftlint fixture: warmup-coverage true positive for the TRAINING
compile-key family — a `TrainStepCompileCache`-style cache whose
``("train_step", bucket, bptt_mode)`` programs are never reachable from
warmup(): the first timed bench sample (or the first optimizer step of a
resumed leg) pays the XLA compile."""


class MiniStepCache:
    def __init__(self):
        self.compile_counts = {}
        self._fns = {}

    def step_fn(self, bucket, bptt_mode):
        count_key = ("train_step", bucket, bptt_mode)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda s, b: (s, b))

    def run(self, state, batch, bucket, bptt_mode):
        return self.step_fn(bucket, bptt_mode)(state, batch)

    def warmup(self):
        # misses step_fn entirely: every (bucket, bptt_mode) program
        # compiles mid-measurement
        return None
