"""graftlint fixture: clean twin of viol_toctou — the operation runs
unguarded and handles FileNotFoundError; pure existence probes and
guards over a DIFFERENT path stay legal."""

import os


def drop_sidecar(path):
    try:
        os.remove(path + ".sha256")
    except FileNotFoundError:
        pass  # already the desired state


def read_meta(path):
    try:
        with open(path) as f:
            return f.read()
    except FileNotFoundError:
        return None


def has_cache(path):
    return os.path.exists(path)  # probe only: nothing guarded


def promote(path):
    if os.path.exists(path + ".complete"):
        # guard and verb name DIFFERENT paths: the marker gates the
        # payload rename, which is not the checked file
        os.replace(path + ".tmp", path)
