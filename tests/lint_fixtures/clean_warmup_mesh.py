"""graftlint fixture: clean twin of viol_warmup_mesh — ONE defining
method builds the compile key for both the single-device and the
sharded family (the shard axis rides as a suffix, exactly the
serve/engine.py pattern), so the one warmup() reaches every family a
mesh engine can dispatch."""


class MiniMeshEngine:
    def __init__(self, mesh_shards=1):
        self.mesh_shards = mesh_shards
        self._shard_suffix = (mesh_shards,) if mesh_shards > 1 else ()
        self.compile_counts = {}
        self._fns = {}

    def _get_window_fn(self, bucket, k):
        count_key = ("decode_window", bucket, k, *self._shard_suffix)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def decode_window(self, tokens, k):
        return self._get_window_fn(len(tokens), k)(tokens)

    def warmup(self):
        # the ONE family-defining method: covered for every shard count
        return self._get_window_fn(1, 4)([0])
