"""graftlint fixture: thread-lifecycle true positive — a daemon worker
thread stored on an attribute and started, with NO stop/close/shutdown
path that joins it or signals its loop (the PR 8 round-3 leaked-poller
class: every retired stack leaks one forever-polling daemon)."""

import threading


class Poller:
    def __init__(self):
        self._thread = None
        self._queue = []

    def ensure_worker(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self.run, name="poller", daemon=True)
            self._thread.start()

    def run(self):
        while True:
            if self._queue:
                self._queue.pop()
