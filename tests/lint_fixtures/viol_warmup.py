"""graftlint fixture: warmup-coverage true positive — two compile-key
families, warmup() dispatches only one. The ("decode_beam", ...) family
compiles in the middle of serving the first beam request."""


class MiniEngine:
    def __init__(self):
        self.compile_counts = {}
        self._fns = {}

    def _get_decode_fn(self, bucket):
        count_key = ("decode", bucket)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def _get_beam_fn(self, bucket, width):
        count_key = ("decode_beam", bucket, width)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def decode(self, tokens):
        return self._get_decode_fn(len(tokens))(tokens)

    def decode_beam(self, tokens, width):
        return self._get_beam_fn(len(tokens), width)(tokens)

    def warmup(self):
        # misses decode_beam: its first real request pays the compile
        return self.decode([0])
