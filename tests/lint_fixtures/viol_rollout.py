"""graftlint fixture: thread-lifecycle true positive for the ROLLOUT
CONTROLLER shape — a serve-side controller whose daemon worker thread
(draining replicas and swapping weights) is stored and started, but with
no stop()/close() path that joins the handle or sets a flag its loop
reads. A rollout loop nobody can park keeps draining replicas while the
server it upgrades is being torn down (the PR 16 contract: the
controller thread is stored on the controller and joined in
``stop()``)."""

import threading


class MiniRollout:
    def __init__(self, server):
        self.server = server
        self._queue = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="mini-rollout", daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            if self._queue:
                self.roll(self._queue.pop(0))

    def roll(self, move):
        return move
