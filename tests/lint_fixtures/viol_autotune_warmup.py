"""graftlint fixture: warmup-coverage true positive for the AUTOTUNER
knob shape — a window dispatcher whose ``("knob_window", k)`` compile-key
family is only reachable through the controller's knob pick, never from
``warmup()``: the first knob move after boot charges a live request the
mid-traffic XLA compile the autotuner exists to avoid (the PR 15
contract: every value a knob can select must be warmup-covered)."""


class MiniKnobEngine:
    def __init__(self, ladder=(1, 4, 8)):
        self.ladder = ladder
        self.window_cap = ladder[-1]
        self.compile_counts = {}
        self._fns = {}

    def window_fn(self, k):
        count_key = ("knob_window", k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda toks: toks[:k])

    def decode(self, toks):
        return self.window_fn(self.window_cap)(toks)

    def warmup(self):
        # never dispatches window_fn: every rung the controller can cap
        # to compiles mid-traffic on its first pick
        return None
