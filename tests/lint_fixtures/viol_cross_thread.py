"""graftlint fixture: cross-thread-state true positive — ``submitted``
is written under the scheduler's lock by submit(), so the lock owns it;
the HTTP-facing stats() reads it (and ``_queue``) with no lock held."""

import threading


class MiniScheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self.submitted = 0
        self.processed = 0

    def submit(self, req):
        with self._lock:
            self._queue.append(req)
            self.submitted += 1

    def step(self):
        with self._lock:
            batch = list(self._queue)
            self._queue.clear()
        # single-writer scheduler state: unguarded on purpose, exempt
        self.processed += len(batch)
        return bool(batch)

    def stats(self):
        return {
            "submitted": self.submitted,  # racy read, no lock
            "queued": len(self._queue),   # racy read, no lock
        }
