"""graftlint fixture: exit-code-literal true positives — magic integers
in all three exit spellings."""

import os
import sys


def gate(failed):
    if failed:
        sys.exit(3)  # collides with whatever else exits 3


def bail(reason):
    raise SystemExit(77)


def hard_kill():
    os._exit(75)
