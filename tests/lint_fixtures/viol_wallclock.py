"""graftlint fixture: wallclock-timing true positives — a latency
measured with the NTP-slewable wall clock, the same read smuggled in
via `from time import time` aliasing, and a datetime.now() subtraction
used as a duration."""

import datetime
import time
from time import time as now


def timed_call(fn):
    t0 = time.time()
    out = fn()
    return out, time.time() - t0


def alias_timed_call(fn):
    t0 = now()
    out = fn()
    return out, now() - t0


def dt_timed_call(fn):
    t0 = datetime.datetime.now()
    out = fn()
    return out, datetime.datetime.now() - t0
