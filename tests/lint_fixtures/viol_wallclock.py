"""graftlint fixture: wallclock-timing true positive — a latency
measured with the NTP-slewable wall clock."""

import time


def timed_call(fn):
    t0 = time.time()
    out = fn()
    return out, time.time() - t0
