"""graftlint fixture: clean twin of viol_exit_code — named constants
from the one exit-code table; messages and exit(0)/main() passthroughs
stay legal."""

import sys

from lstm_tensorspark_tpu.resilience.exit_codes import ANOMALY_RC, WEDGE_RC


def main():
    return 0


def gate(failed, regression_rc):
    if failed:
        sys.exit(regression_rc)  # named, routed by the caller


def bail(reason):
    raise SystemExit(f"fatal: {reason}")  # message form exits 1


def anomaly_abort():
    raise SystemExit(ANOMALY_RC)


def wedge_exit():
    sys.exit(WEDGE_RC)


def ok():
    sys.exit(0)  # the universal success constant


if __name__ == "__main__":
    raise SystemExit(main())
