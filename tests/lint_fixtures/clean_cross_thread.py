"""graftlint fixture: clean twin of viol_cross_thread — stats() takes
the lock for its snapshot; the scheduler-thread closure (step) keeps its
single-writer exemption, and a *_locked helper asserts the held-lock
calling contract instead of re-acquiring."""

import threading


class MiniScheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self.submitted = 0
        self.processed = 0

    def submit(self, req):
        with self._lock:
            self._queue.append(req)
            self.submitted += 1

    def step(self):
        with self._lock:
            batch = self._drain_locked()
        self.processed += len(batch)  # scheduler-owned: exempt
        return bool(batch)

    def _drain_locked(self):
        batch = list(self._queue)
        self._queue.clear()
        return batch

    def stats(self):
        with self._lock:
            return {
                "submitted": self.submitted,
                "queued": len(self._queue),
            }
