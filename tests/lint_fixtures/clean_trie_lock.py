"""graftlint fixture: the prefix-trie shared-RLock pattern that must
NOT fire.

Exactly the PrefixTrie/StateCache arrangement: the trie overlay shares
the slot cache's reentrant lock (``self._lock = cache._lock``), so the
eviction listener already runs under the only lock the trie ever
takes, and trie methods re-enter cache methods under it. One merged
reentrant lock has no order to violate — this is the sanctioned
design, not an ABBA (contrast viol_trie_lock.py)."""

import threading


class SlotCache:
    def __init__(self):
        self._lock = threading.RLock()
        self._slots = {}
        self._pinned = set()
        self.evict_listeners = []

    def pin(self, sid):
        with self._lock:
            self._pinned.add(sid)

    def evict(self, sid):
        with self._lock:
            slot = self._slots.pop(sid, None)
            for listener in self.evict_listeners:
                listener(sid, slot)


class Trie:
    def __init__(self, cache: SlotCache):
        self.cache = cache
        self._lock = cache._lock  # shared on purpose (see module doc)
        self._nodes = {}
        cache.evict_listeners.append(self._on_slot_evicted_locked)

    def lookup(self, key):
        with self._lock:
            node = self._nodes.get(key)
            if node is not None:
                self.cache.pin(node["sid"])  # reentrant: same merged lock
            return node

    def _on_slot_evicted_locked(self, sid, slot):
        # fired under the shared lock; taking it again would merely
        # re-enter, so the body stays lock-free by convention
        self._nodes.pop(sid, None)
