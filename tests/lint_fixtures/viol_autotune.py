"""graftlint fixture: thread-lifecycle true positive for the AUTOTUNER
shape — a serve controller whose daemon control-loop thread is stored on
the tuner and started, but with NO stop()/close() path that joins it or
signals a flag its loop reads. A controller nobody can park keeps moving
knobs while the server it steers is being torn down (the PR 15 contract:
the thread is stored on the tuner and joined in ``stop()``)."""

import threading


class MiniTuner:
    def __init__(self, server):
        self.server = server
        self._thread = None
        self.ticks = 0

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="mini-autotuner", daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            self.tick()

    def tick(self):
        self.ticks += 1
