"""graftlint fixture: warmup-coverage true positive for the SHARDED
compile-key family — the mesh engine's window program family grows a
trailing shard axis (("decode_window", bucket, K, sampling, shards)) in
its own defining method, but warmup() only reaches the single-device
family's method: the first request a sharded engine serves pays the
XLA compile mid-traffic."""


class MiniMeshEngine:
    def __init__(self, mesh_shards=1):
        self.mesh_shards = mesh_shards
        self.compile_counts = {}
        self._fns = {}

    def _get_window_fn(self, bucket, k):
        count_key = ("decode_window", bucket, k)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def _get_window_sharded_fn(self, bucket, k):
        count_key = ("decode_window", bucket, k, self.mesh_shards)
        self.compile_counts[count_key] = (
            self.compile_counts.get(count_key, 0) + 1)
        return self._fns.setdefault(count_key, lambda t: t)

    def decode_window(self, tokens, k):
        if self.mesh_shards > 1:
            return self._get_window_sharded_fn(len(tokens), k)(tokens)
        return self._get_window_fn(len(tokens), k)(tokens)

    def warmup(self):
        # only the single-device family: a sharded engine compiles its
        # window program in the middle of serving traffic
        return self._get_window_fn(1, 4)([0])
