"""graftlint fixture: io-under-lock true positive for the network
shapes — the remote affinity probe does a bounded HTTP GET under the
router's global admission lock (ISSUE 17: one slow peer stalled every
admission, health probe and scheduler iteration behind the network)."""

import json
import threading
import urllib.request


class PeerTransport:
    def __init__(self, url):
        self.url = url

    def rpc_get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=5.0) as resp:
            return json.loads(resp.read().decode("utf-8"))


class Router:
    def __init__(self, transport: PeerTransport):
        self.transport = transport
        self._lock = threading.Lock()

    def has_session(self, sid):
        with self._lock:
            # blocking HTTP round-trip under the global admission lock:
            # every submit()/drain() queues behind one peer's latency
            hb = self.transport.rpc_get("/replica/heartbeat")
            return sid in hb.get("session_ids", ())
