"""Sequence-parallel wavefront scan: exact parity with the serial scan,
gradients included, for several microbatch settings."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from lstm_tensorspark_tpu.ops import init_lstm_params, lstm_scan
from lstm_tensorspark_tpu.parallel import make_mesh
from lstm_tensorspark_tpu.parallel.sequence_parallel import sp_lstm_scan

B, T, D, H = 4, 32, 5, 8


@pytest.fixture(scope="module")
def setup():
    params = init_lstm_params(jax.random.PRNGKey(0), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    return params, xs


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_sp_matches_serial(setup, microbatches):
    params, xs = setup
    mesh = make_mesh(dp=1, tp=1, sp=8)
    fn = jax.jit(
        shard_map(
            lambda p, x: sp_lstm_scan(p, x, microbatches=microbatches),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    ys_sp = fn(params, xs)
    _, ys = lstm_scan(params, xs)
    np.testing.assert_allclose(ys_sp, ys, rtol=1e-5, atol=1e-6)


def test_sp_grads_match_serial(setup):
    params, xs = setup
    mesh = make_mesh(dp=1, tp=1, sp=8)

    def sp_loss(p, x):
        ys = shard_map(
            lambda p_, x_: sp_lstm_scan(p_, x_, microbatches=2),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )(p, x)
        return jnp.mean(ys**2)

    def serial_loss(p, x):
        _, ys = lstm_scan(p, x)
        return jnp.mean(ys**2)

    l1, g1 = jax.value_and_grad(sp_loss)(params, xs)
    l2, g2 = jax.value_and_grad(serial_loss)(params, xs)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_sp_with_remat(setup):
    params, xs = setup
    mesh = make_mesh(dp=1, tp=1, sp=8)
    fn = jax.jit(
        shard_map(
            lambda p, x: sp_lstm_scan(p, x, microbatches=2, remat_chunk=2),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    ys_sp = fn(params, xs)
    _, ys = lstm_scan(params, xs)
    np.testing.assert_allclose(ys_sp, ys, rtol=1e-5, atol=1e-6)


def test_sp_pallas_interpret_matches_serial():
    """The fused kernel INSIDE the wavefront (VERDICT r3 item 4): each
    device's chunk runs pallas_lstm_scan (interpret mode on CPU) with the
    carry handed between devices via ppermute — outputs must match the
    serial scan exactly like the plain-scan wavefront does."""
    params = init_lstm_params(jax.random.PRNGKey(2), D, 128)
    xs = jax.random.normal(jax.random.PRNGKey(3), (8, T, D))
    mesh = make_mesh(dp=1, tp=1, sp=8)
    fn = jax.jit(
        shard_map(
            lambda p, x: sp_lstm_scan(p, x, microbatches=1, use_pallas=True,
                                      pallas_interpret=True),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    ys_sp = fn(params, xs)
    _, ys = lstm_scan(params, xs)
    np.testing.assert_allclose(ys_sp, ys, rtol=1e-5, atol=1e-5)


def test_sp_pallas_interpret_grads_match_serial():
    """BPTT through kernel-chunk wavefront: the custom VJP runs per chunk
    and the carry cotangents ride the transposed ppermute chain."""
    params = init_lstm_params(jax.random.PRNGKey(4), D, 128)
    xs = jax.random.normal(jax.random.PRNGKey(5), (8, T, D))
    mesh = make_mesh(dp=1, tp=1, sp=8)

    def sp_loss(p, x):
        ys = shard_map(
            lambda p_, x_: sp_lstm_scan(p_, x_, microbatches=2,
                                        use_pallas=True,
                                        pallas_interpret=True),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )(p, x)
        return jnp.mean(ys**2)

    def serial_loss(p, x):
        _, ys = lstm_scan(p, x)
        return jnp.mean(ys**2)

    l1, g1 = jax.value_and_grad(sp_loss)(params, xs)
    l2, g2 = jax.value_and_grad(serial_loss)(params, xs)
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(a, b_, rtol=2e-4, atol=1e-5),
        g1, g2,
    )


def test_sp_train_step_all_manual_with_pallas_cfg():
    """make_sharded_lm_train_step with cfg.use_pallas=True (no TP) goes
    ALL-manual (every mesh axis) — on the CPU mesh the kernel itself
    falls back per the platform gate, so this checks the all-manual
    shard_map construction compiles and matches the partially-manual
    program step for step."""
    import optax

    from lstm_tensorspark_tpu.models import LMConfig, init_lm
    from lstm_tensorspark_tpu.parallel.train_step import (
        make_sharded_lm_train_step,
    )
    from lstm_tensorspark_tpu.train.loop import init_train_state

    mesh = make_mesh(dp=4, tp=1, sp=2)
    data = jax.random.randint(jax.random.PRNGKey(6), (8, 33), 0, 50)
    batch = {"inputs": data[:, :-1], "targets": data[:, 1:]}

    def run(use_pallas):
        cfg = LMConfig(vocab_size=50, hidden_size=16, num_layers=1,
                       use_pallas=use_pallas)
        params = init_lm(jax.random.PRNGKey(7), cfg)
        opt = optax.sgd(0.3)
        step = make_sharded_lm_train_step(cfg, opt, mesh, params,
                                          microbatches=2, donate=False)
        state = init_train_state(params, opt, jax.random.PRNGKey(8))
        losses = []
        for _ in range(4):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    np.testing.assert_allclose(run(True), run(False), rtol=1e-6, atol=1e-6)
