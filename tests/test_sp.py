"""Sequence-parallel wavefront scan: exact parity with the serial scan,
gradients included, for several microbatch settings."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from lstm_tensorspark_tpu.ops import init_lstm_params, lstm_scan
from lstm_tensorspark_tpu.parallel import make_mesh
from lstm_tensorspark_tpu.parallel.sequence_parallel import sp_lstm_scan

B, T, D, H = 4, 32, 5, 8


@pytest.fixture(scope="module")
def setup():
    params = init_lstm_params(jax.random.PRNGKey(0), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    return params, xs


@pytest.mark.parametrize("microbatches", [1, 2, 4])
def test_sp_matches_serial(setup, microbatches):
    params, xs = setup
    mesh = make_mesh(dp=1, tp=1, sp=8)
    fn = jax.jit(
        shard_map(
            lambda p, x: sp_lstm_scan(p, x, microbatches=microbatches),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    ys_sp = fn(params, xs)
    _, ys = lstm_scan(params, xs)
    np.testing.assert_allclose(ys_sp, ys, rtol=1e-5, atol=1e-6)


def test_sp_grads_match_serial(setup):
    params, xs = setup
    mesh = make_mesh(dp=1, tp=1, sp=8)

    def sp_loss(p, x):
        ys = shard_map(
            lambda p_, x_: sp_lstm_scan(p_, x_, microbatches=2),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )(p, x)
        return jnp.mean(ys**2)

    def serial_loss(p, x):
        _, ys = lstm_scan(p, x)
        return jnp.mean(ys**2)

    l1, g1 = jax.value_and_grad(sp_loss)(params, xs)
    l2, g2 = jax.value_and_grad(serial_loss)(params, xs)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)
    jax.tree.map(
        lambda a, b_: np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-6),
        g1, g2,
    )


def test_sp_with_remat(setup):
    params, xs = setup
    mesh = make_mesh(dp=1, tp=1, sp=8)
    fn = jax.jit(
        shard_map(
            lambda p, x: sp_lstm_scan(p, x, microbatches=2, remat_chunk=2),
            mesh=mesh,
            in_specs=(P(), P(None, "seq")),
            out_specs=P(None, "seq"),
            check_vma=False,
        )
    )
    ys_sp = fn(params, xs)
    _, ys = lstm_scan(params, xs)
    np.testing.assert_allclose(ys_sp, ys, rtol=1e-5, atol=1e-6)
