"""Parallel-scan BPTT (ops/parallel_scan.py): gradient parity of
``bptt="assoc"`` against the sequential VJP across the acceptance matrix
({1,2}-layer x {masked, unmasked} x {remat on/off} x bidir), the
fp64-validated tolerance case, the auto-resolution policy + `plan_bytes`
memory model, the remat-divisibility contract shared by both modes, and
the trace-time counters surfaced in metrics_snapshot records.

Tolerance rationale (see test_fp64_validates_f32_tolerances): both the
sequential VJP and the assoc backward are f32 computations that differ
from the f64 ground truth by < ~2e-5 relative on these shapes; the
parity tolerances below (5e-4 rel / 5e-5 abs for f32) sit an order of
magnitude above that envelope, so a real algebra bug cannot hide inside
accumulated rounding."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lstm_tensorspark_tpu.ops import (
    bidir_lstm_scan,
    init_lstm_params,
    lstm_scan,
    lstm_step_unfused,
    stacked_lstm_scan,
)
from lstm_tensorspark_tpu.ops import parallel_scan


F32_TOL = dict(rtol=5e-4, atol=5e-5)
BF16_TOL = dict(rtol=3e-2, atol=3e-3)


def _mk_mask(rng, B, T):
    lens = rng.randint(1, T + 1, size=B)
    return jnp.asarray((np.arange(T)[None, :] < lens[:, None]), jnp.float32)


def _stacked_loss(layer_params, xs, mask, *, bptt, remat_chunk=None,
                  compute_dtype=None):
    def loss(params_and_xs):
        lp, x = params_and_xs
        finals, ys = stacked_lstm_scan(
            lp, x, mask=mask, bptt=bptt, remat_chunk=remat_chunk,
            compute_dtype=compute_dtype,
        )
        out = jnp.sum(ys ** 2)
        for (h, c) in finals:
            out = out + jnp.sum(h * 0.5) + jnp.sum(c * 0.25)
        return out
    return loss


@pytest.mark.parametrize("layers", [1, 2])
@pytest.mark.parametrize("masked", [False, True])
@pytest.mark.parametrize("remat", [None, 4])
def test_grad_parity_stacked(layers, masked, remat):
    """The acceptance matrix: {1,2}-layer x {masked, unmasked} x
    {remat on/off} — assoc grads allclose to the sequential VJP."""
    rng = np.random.RandomState(layers * 10 + int(masked) * 3 + (remat or 0))
    B, T, D, H = 3, 16, 5, 6
    keys = jax.random.split(jax.random.PRNGKey(7), layers)
    lp = [init_lstm_params(keys[0], D, H)]
    for k in keys[1:]:
        lp.append(init_lstm_params(k, H, H))
    xs = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    mask = _mk_mask(rng, B, T) if masked else None

    g_seq = jax.grad(_stacked_loss(lp, xs, mask, bptt="sequential",
                                   remat_chunk=remat))((lp, xs))
    g_asc = jax.grad(_stacked_loss(lp, xs, mask, bptt="assoc",
                                   remat_chunk=remat))((lp, xs))
    for a, b in zip(jax.tree.leaves(g_asc), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **F32_TOL)


@pytest.mark.parametrize("masked", [False, True])
def test_grad_parity_bidir(masked):
    """bidir_lstm_scan: both directions' grads agree across modes (the
    reversed scan exercises the flip plumbing in assoc_lstm_scan)."""
    rng = np.random.RandomState(17 + int(masked))
    B, T, D, H = 2, 12, 4, 5
    pf = init_lstm_params(jax.random.PRNGKey(0), D, H)
    pb = init_lstm_params(jax.random.PRNGKey(1), D, H)
    xs = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    mask = _mk_mask(rng, B, T) if masked else None

    def loss(bptt):
        def L(args):
            f, b, x = args
            ((hf, cf), ysf), ((hb, cb), ysb) = bidir_lstm_scan(
                f, b, x, mask=mask, bptt=bptt)
            return (jnp.sum(ysf ** 2) + jnp.sum(ysb ** 2)
                    + jnp.sum(hf) + jnp.sum(hb)
                    + 0.5 * (jnp.sum(cf) + jnp.sum(cb)))
        return L

    g_seq = jax.grad(loss("sequential"))((pf, pb, xs))
    g_asc = jax.grad(loss("assoc"))((pf, pb, xs))
    for a, b in zip(jax.tree.leaves(g_asc), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **F32_TOL)


def test_bf16_params_fp32_grads_parity():
    """compute_dtype=bfloat16 (bf16 matmuls, f32 accumulation/grads):
    the two backwards agree within the bf16 rounding envelope."""
    rng = np.random.RandomState(23)
    B, T, D, H = 2, 16, 4, 8
    lp = [init_lstm_params(jax.random.PRNGKey(2), D, H)]
    xs = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    mask = _mk_mask(rng, B, T)
    g_seq = jax.grad(_stacked_loss(lp, xs, mask, bptt="sequential",
                                   compute_dtype=jnp.bfloat16))((lp, xs))
    g_asc = jax.grad(_stacked_loss(lp, xs, mask, bptt="assoc",
                                   compute_dtype=jnp.bfloat16))((lp, xs))
    for a, b in zip(jax.tree.leaves(g_asc), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **BF16_TOL)
        assert a.dtype == b.dtype  # grads stay in the param/input dtype


def test_forward_values_identical():
    """The assoc path only swaps the VJP: forward ys and final carries
    match the sequential scan to f32 round-off."""
    rng = np.random.RandomState(5)
    B, T, D, H = 3, 24, 4, 6
    p = init_lstm_params(jax.random.PRNGKey(5), D, H)
    xs = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    mask = _mk_mask(rng, B, T)
    for kw in (dict(), dict(mask=mask), dict(mask=mask, reverse=True)):
        (h1, c1), ys1 = lstm_scan(p, xs, bptt="sequential", **kw)
        (h2, c2), ys2 = lstm_scan(p, xs, bptt="assoc", **kw)
        np.testing.assert_allclose(np.asarray(ys1), np.asarray(ys2),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2),
                                   rtol=1e-6, atol=1e-6)


def test_fp64_validates_f32_tolerances():
    """Ground the parity tolerances in fp64: a step-at-a-time f64 oracle
    (lstm_step_unfused is dtype-generic) gives the true gradient; BOTH
    f32 backwards must sit within the envelope the parity tests assume.
    This is what makes the F32_TOL above a validated bound rather than a
    number that happens to pass."""
    rng = np.random.RandomState(31)
    B, T, D, H = 2, 16, 4, 6
    p32 = init_lstm_params(jax.random.PRNGKey(3), D, H)
    xs32 = jnp.asarray(rng.randn(B, T, D), jnp.float32)

    jax.config.update("jax_enable_x64", True)
    try:
        p64 = jax.tree.map(lambda a: jnp.asarray(np.asarray(a), jnp.float64),
                           p32)
        xs64 = jnp.asarray(np.asarray(xs32), jnp.float64)

        def oracle_loss(args):
            p, x = args
            h = jnp.zeros((B, H), x.dtype)
            c = jnp.zeros((B, H), x.dtype)
            out = jnp.zeros((), x.dtype)
            for t in range(T):
                (h, c), _ = lstm_step_unfused(p, (h, c), x[:, t])
                out = out + jnp.sum(h ** 2)
            return out + jnp.sum(h * 0.5) + jnp.sum(c * 0.25)

        g64 = jax.jit(jax.grad(oracle_loss))((p64, xs64))
        g64 = [np.asarray(a, np.float64) for a in jax.tree.leaves(g64)]
    finally:
        jax.config.update("jax_enable_x64", False)

    def f32_loss(bptt):
        def L(args):
            p, x = args
            (h, c), ys = lstm_scan(p, x, bptt=bptt)
            return jnp.sum(ys ** 2) + jnp.sum(h * 0.5) + jnp.sum(c * 0.25)
        return L

    g_seq = jax.tree.leaves(jax.grad(f32_loss("sequential"))((p32, xs32)))
    g_asc = jax.tree.leaves(jax.grad(f32_loss("assoc"))((p32, xs32)))
    for ga, gs, gt in zip(g_asc, g_seq, g64):
        # both f32 paths inside the envelope the parity tolerance assumes
        np.testing.assert_allclose(np.asarray(gs, np.float64), gt,
                                   rtol=5e-5, atol=5e-6)
        np.testing.assert_allclose(np.asarray(ga, np.float64), gt,
                                   rtol=5e-5, atol=5e-6)


# ---- policy / plan / counters ----


def test_resolve_bptt_policy(monkeypatch):
    st0 = parallel_scan.assoc_stats()
    # explicit modes honored as written
    assert parallel_scan.resolve_bptt("sequential", 8, 400, 64) == "sequential"
    assert parallel_scan.resolve_bptt("assoc", 8, 8, 64) == "assoc"
    # auto below the T threshold -> sequential, counted
    assert parallel_scan.resolve_bptt("auto", 8, 32, 64) == "sequential"
    # auto long enough + plan fits -> assoc
    assert parallel_scan.resolve_bptt("auto", 8, 400, 64) == "assoc"
    # plan miss (budget forced to 0) -> sequential, counted
    monkeypatch.setenv("LSTM_TSP_ASSOC_BUDGET_MB", "0")
    assert parallel_scan.resolve_bptt("auto", 8, 400, 64) == "sequential"
    st1 = parallel_scan.assoc_stats()
    assert st1["sequential_fallbacks"] - st0["sequential_fallbacks"] == 2
    with pytest.raises(ValueError, match="bptt="):
        parallel_scan.resolve_bptt("parallel", 8, 400, 64)


def test_plan_bytes_model():
    # monotone in every dimension
    base = parallel_scan.plan_bytes(8, 400, 64)
    assert parallel_scan.plan_bytes(16, 400, 64) > base
    assert parallel_scan.plan_bytes(8, 800, 64) > base
    assert parallel_scan.plan_bytes(8, 400, 128) > base
    # the dense chunk-operator term dominates at large H (the reason the
    # plan gates assoc at all): quadratic-in-H growth
    assert (parallel_scan.plan_bytes(8, 400, 256)
            > 8 * parallel_scan.plan_bytes(8, 400, 64))
    # imdb_bilstm's H=256 x B=64 shape must MISS the default budget (auto
    # stays sequential there until a TPU-sized budget is configured)
    assert not parallel_scan.plan_fits(64, 400, 256)
    assert parallel_scan.plan_fits(8, 400, 64)


def test_pick_tile():
    assert parallel_scan.pick_tile(400) == 16
    assert parallel_scan.pick_tile(400, remat_chunk=25) == 25  # fwd chunking wins
    assert parallel_scan.pick_tile(400, remat_chunk=7) == 16   # non-divisor ignored
    assert parallel_scan.pick_tile(7) == 7                     # prime -> one chunk
    assert parallel_scan.pick_tile(1) == 1


def test_remat_divisibility_raises_in_both_modes():
    """The satellite contract: T not divisible by remat_chunk fails
    loudly in EVERY bptt mode — a silent tail chunk could give the modes
    different step groupings for identical inputs."""
    p = init_lstm_params(jax.random.PRNGKey(0), 3, 4)
    xs = jnp.zeros((2, 10, 3), jnp.float32)
    for mode in ("sequential", "assoc"):
        with pytest.raises(ValueError, match="not divisible by remat_chunk"):
            lstm_scan(p, xs, remat_chunk=4, bptt=mode)


def test_assoc_trace_counter_and_metrics_snapshot(tmp_path):
    """The trace-time counters reach the metrics_snapshot JSONL record
    (the supervised-restart mode-flip signal): train a hand-driven step
    with bptt='assoc', then log a registry snapshot with the cli-style
    extra dict and check the record round-trips."""
    from lstm_tensorspark_tpu import obs
    from lstm_tensorspark_tpu.train.loop import (
        init_train_state, make_train_step, train_loop)
    from lstm_tensorspark_tpu.train.metrics import MetricsLogger
    import optax

    rng = np.random.RandomState(0)
    p = [init_lstm_params(jax.random.PRNGKey(0), 4, 4)]
    xs = jnp.asarray(rng.randn(2, 16, 4), jnp.float32)

    def loss_fn(params, batch, rng_):
        _, ys = stacked_lstm_scan(params, batch, bptt="assoc")
        return jnp.sum(ys ** 2), {"loss": jnp.sum(ys ** 2)}

    opt = optax.sgd(1e-2)
    state = init_train_state(p, opt, jax.random.PRNGKey(1))
    step = make_train_step(loss_fn, opt)
    tr_counter = obs.REGISTRY.counter(
        "train_bptt_assoc_traces_total",
        "scans traced with the associative-scan backward")
    before = tr_counter.value
    train_loop(state, step, iter([xs]), num_steps=1, log_every=0)
    assert tr_counter.value >= before + 1

    path = tmp_path / "m.jsonl"
    with MetricsLogger(str(path), quiet=True) as logger:
        logger.log_registry(
            obs.REGISTRY,
            extra={"bptt_mode": "assoc",
                   **{f"bptt_{k}": v
                      for k, v in parallel_scan.assoc_stats().items()}})
    rec = json.loads(path.read_text().splitlines()[-1])
    assert rec["bptt_mode"] == "assoc"
    assert rec["bptt_assoc_traces"] >= 1
    assert "train_bptt_assoc_traces_total" in rec


def test_train_step_compile_cache_warm_lattice():
    """TrainStepCompileCache (train/device_step.py): warmup traces each
    (bucket, bptt_mode) program exactly once, replays hit the cached
    executable (no re-trace), and the compile-key family is the
    graftlint-gated ``("train_step", bucket, bptt_mode)`` shape."""
    import optax
    from lstm_tensorspark_tpu.train import TrainStepCompileCache
    from lstm_tensorspark_tpu.train.loop import (
        init_train_state, make_train_step)

    opt = optax.sgd(0.1)
    p = [init_lstm_params(jax.random.PRNGKey(0), 4, 4)]

    def builder(bucket, bptt_mode):
        def loss_fn(params, batch, rng_):
            _, ys = stacked_lstm_scan(params, batch, bptt=bptt_mode)
            return jnp.sum(ys ** 2), {"loss": jnp.sum(ys ** 2)}
        return make_train_step(loss_fn, opt, jit=False)

    cache = TrainStepCompileCache(builder)
    batch = jnp.zeros((2, 8, 4), jnp.float32)
    bucket = (2, 8, 4)
    state = init_train_state(p, opt, jax.random.PRNGKey(1))
    cache.warmup([(bucket, m, state, batch)
                  for m in ("sequential", "assoc")])
    assert cache.compile_counts == {
        ("train_step", bucket, "sequential"): 1,
        ("train_step", bucket, "assoc"): 1,
    }
    # replay: cached executable, count unchanged
    cache.step_fn(bucket, "assoc")(state, batch)
    assert cache.compile_counts[("train_step", bucket, "assoc")] == 1


def test_carry_and_stateful_parity():
    """Nonzero initial carries (stateful TBPTT windows) flow correct
    gradients through the assoc backward, including the carry grad."""
    rng = np.random.RandomState(11)
    B, T, D, H = 2, 16, 4, 6
    p = init_lstm_params(jax.random.PRNGKey(4), D, H)
    xs = jnp.asarray(rng.randn(B, T, D), jnp.float32)
    c0 = (jnp.asarray(rng.randn(B, H), jnp.float32),
          jnp.asarray(rng.randn(B, H), jnp.float32))
    mask = _mk_mask(rng, B, T)

    def loss(bptt):
        def L(args):
            pp, x, cc = args
            (h, c), ys = lstm_scan(pp, x, cc, mask=mask, bptt=bptt)
            return jnp.sum(ys ** 2) + jnp.sum(h) + jnp.sum(c * 0.5)
        return L

    g_seq = jax.grad(loss("sequential"))((p, xs, c0))
    g_asc = jax.grad(loss("assoc"))((p, xs, c0))
    for a, b in zip(jax.tree.leaves(g_asc), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **F32_TOL)
