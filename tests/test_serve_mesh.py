"""Mesh-spanning serving (ISSUE 14): the tensor-parallel mesh replica
(``ServeEngine(mesh_shards=N)`` — params + state-cache slots sharded
over a ("model",) device mesh via the training GSPMD specs) and the
remote-replica RPC transport (serve/remote.py) behind the router.

Pins: token-identical greedy AND temperature-sampled parity of the
sharded engine vs the single-device engine vs models/generate.py on the
conftest virtual devices; shard-axis compile keys; the loud (counted)
pallas→scan fallback on sharded engines; detach/restore and tier
spill/fill over sharded slots; the router treating a mesh replica as
just another replica; and the 2-process host-kill drill — SIGKILLing a
remote replica host loses ZERO kept sessions (continuations resume
token-identically from the shared ``--session-dir`` disk tier on the
survivor)."""

import os
import sys
import tempfile
import threading
import time

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.obs import MetricsRegistry
from lstm_tensorspark_tpu.serve import (
    RemoteReplica,
    SamplingParams,
    ServeEngine,
    ServeServer,
)
from lstm_tensorspark_tpu.serve.engine import GREEDY
from lstm_tensorspark_tpu.serve.server import make_http_server
from lstm_tensorspark_tpu.serve.state_cache import (
    session_file_path as _session_file,
)
from tools.serve_proc import boot_serve_http_or_raise

_CFG = LMConfig(vocab_size=31, hidden_size=16, num_layers=2)
SHARDS = 2


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(5), _CFG)


def _engine(params, shards, *, seed=0, **kw):
    kw.setdefault("num_slots", 8)
    kw.setdefault("prefill_buckets", (4, 8))
    kw.setdefault("batch_buckets", (1, 2, 4))
    kw.setdefault("registry", MetricsRegistry())
    return ServeEngine(params, _CFG, rng_seed=seed, mesh_shards=shards,
                       **kw)


def _server(engine, **kw):
    kw.setdefault("max_active", 4)
    kw.setdefault("queue_size", 16)
    kw.setdefault("window_ladder", (1, 4))
    return ServeServer(engine, **kw)


def _prompts(n, seed=0, lo=2, hi=8):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, _CFG.vocab_size,
                        size=rng.randint(lo, hi + 1)).astype(np.int32)
            for _ in range(n)]


def _serve_all(server, prompts, sampling=GREEDY, max_new=6):
    out = []
    with server:
        server.warmup(sampling, prompt_lens=(8,))
        for p in prompts:
            out.append(list(server.generate(
                p, max_new_tokens=max_new, sampling=sampling).tokens))
    return out


# ---- parity: sharded engine vs single-device vs models/generate --------


def test_mesh_greedy_parity_vs_single_and_generate(params):
    prompts = _prompts(4, seed=1)
    single = _serve_all(_server(_engine(params, 1)), prompts)
    mesh = _serve_all(_server(_engine(params, SHARDS)), prompts)
    assert mesh == single
    gen = make_generate_fn(_CFG, max_new_tokens=6, greedy=True)
    ref = [
        np.asarray(gen(params, p[None, :], jax.random.PRNGKey(0))
                   )[0, p.size:].tolist()
        for p in prompts
    ]
    assert mesh == ref


def test_mesh_sampled_parity(params):
    """Temperature-sampled parity: same engine rng chain + same dispatch
    order ⇒ the sharded engine must emit the SAME tokens (the Gumbel
    draws are identical; a sharded logits psum must not flip any
    argmax-after-noise)."""
    sa = SamplingParams(temperature=0.8)
    prompts = _prompts(4, seed=2)

    def engine_tokens(engine):
        engine.warmup(sa, prompt_lens=(8,), windows=(4,))
        toks = []
        for i, p in enumerate(prompts):
            sid = f"x{i}"
            slot, fresh = engine.cache.acquire_pinned(sid)
            first = int(engine.prefill([(slot, fresh, p)], sa)[0])
            win = engine.decode_window([slot], [first], [5],
                                       sampling=sa, window=4)
            row = engine.fetch_window(win)[0]
            toks.append([first] + [int(t) for t in row if t >= 0])
            engine.cache.release(sid)
        return toks

    assert (engine_tokens(_engine(params, 1, seed=7))
            == engine_tokens(_engine(params, SHARDS, seed=7)))


def test_mesh_compile_keys_carry_shard_axis(params):
    e = _engine(params, SHARDS)
    e.warmup(GREEDY, prompt_lens=(4,), windows=(4,))
    keys = set(e.compile_counts)
    assert keys, "warmup compiled nothing"
    assert all(k[-1] == SHARDS for k in keys), keys
    assert any(k[0] == "decode_window" for k in keys)
    assert e.stats()["mesh_shards"] == SHARDS
    # single-device engines keep the legacy key arity
    e1 = _engine(params, 1)
    e1.warmup(GREEDY, prompt_lens=(4,), windows=(4,))
    assert all(k[-1] != SHARDS or isinstance(k[-1], tuple)
               for k in e1.compile_counts)


def test_mesh_pallas_falls_back_loudly(params, capsys):
    """--decode-kernel pallas on a sharded engine: boot-time log line,
    every window dispatched as the scan program, fallbacks counted —
    never a crash, never a silent re-resolve."""
    e = _engine(params, SHARDS, decode_kernel="pallas")
    assert "not supported on a 2-shard mesh engine" in capsys.readouterr().out
    assert e.decode_kernel == "pallas"  # the request is recorded honestly
    e.warmup(GREEDY, prompt_lens=(4,), windows=(4,))
    assert e.decode_window_scan_fallbacks > 0
    assert not any(k[0] == "decode_window_pallas" for k in e.compile_counts)
    # "auto" resolves to scan on a mesh engine without counting fallbacks
    ea = _engine(params, SHARDS, decode_kernel="auto")
    assert ea.decode_kernel == "scan"
    ea.warmup(GREEDY, prompt_lens=(4,), windows=(4,))
    assert ea.decode_window_scan_fallbacks == 0


def test_mesh_engine_rejects_bad_shapes(params):
    with pytest.raises(ValueError, match="not divisible"):
        ServeEngine(params, LMConfig(vocab_size=31, hidden_size=15),
                    mesh_shards=2, registry=MetricsRegistry())
    with pytest.raises(ValueError, match="device"):
        _engine(params, SHARDS, device=jax.devices()[0])


# ---- session lifecycle over sharded slots ------------------------------


def test_mesh_detach_restore_token_identical(params):
    e = _engine(params, SHARDS)
    srv = _server(e)
    prompt = np.arange(1, 6, dtype=np.int32)
    with srv:
        srv.warmup(prompt_lens=(8,))
        first = srv.generate(prompt, max_new_tokens=3, keep_session=True)
        sid = first.session_id
        state = e.detach_session(sid)
        assert state.h.shape == (_CFG.num_layers, _CFG.hidden_size)
        e.restore_session(sid, state)
        cont = srv.generate([first.tokens[-1]], max_new_tokens=3,
                            session_id=sid, keep_session=True)
    gen = make_generate_fn(_CFG, max_new_tokens=6, greedy=True)
    ref = np.asarray(gen(params, prompt[None, :], jax.random.PRNGKey(0))
                     )[0, prompt.size:]
    assert list(first.tokens) + list(cont.tokens) == ref.tolist()


def test_mesh_tier_spill_fill_token_identical(params, tmp_path):
    """Tier fill/spill over SHARDED slots: 3 kept sessions over 2 slots
    force evictions (async spill of sharded rows) and continuation
    fills — every conversation must match the ample-slots single-device
    reference token for token."""

    def conversations(engine, max_active=2):
        srv = _server(engine, max_active=max_active)
        toks = []
        with srv:
            srv.warmup(prompt_lens=(8,))
            sids = []
            for i in range(3):
                r = srv.generate([i + 1, i + 2, 3], max_new_tokens=4,
                                 keep_session=True)
                sids.append(r.session_id)
                toks.append(list(r.tokens))
            for _ in range(2):
                for i, sid in enumerate(sids):
                    r = srv.generate([toks[i][-1]], max_new_tokens=4,
                                     session_id=sid, keep_session=True)
                    toks[i].extend(r.tokens)
        return toks

    mesh = conversations(_engine(
        params, SHARDS, num_slots=2,
        session_dir=str(tmp_path / "mesh_tiers")))
    ref = conversations(_engine(params, 1), max_active=4)
    assert mesh == ref


# ---- the router's view of a mesh replica -------------------------------


def test_router_treats_mesh_replica_as_one_replica(params):
    """A mixed fleet — replica 0 sharded, replica 1 single-device —
    behind one router: health fans in 2 replicas, both serve traffic,
    and greedy output is token-identical to models/generate.py whichever
    replica decodes it."""
    reg = MetricsRegistry()
    engines = [
        _engine(params, SHARDS, seed=0, registry=reg),
        _engine(params, 1, seed=1, registry=reg),
    ]
    srv = _server(engines)
    prompts = _prompts(6, seed=3)
    results: list = [None] * len(prompts)
    replicas: list = [None] * len(prompts)
    with srv:
        srv.warmup(prompt_lens=(8,))
        h = srv.health()
        assert h["status"] == "ok" and h["replicas_total"] == 2

        def one(i):
            r = srv.generate(prompts[i], max_new_tokens=6)
            results[i] = list(r.tokens)
            replicas[i] = r.replica

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        routed = srv.router.stats()["routed"]
    assert set(replicas) == {0, 1}, replicas
    assert sum(routed.values()) == len(prompts)
    gen = make_generate_fn(_CFG, max_new_tokens=6, greedy=True)
    for p, got in zip(prompts, results):
        ref = np.asarray(gen(params, p[None, :], jax.random.PRNGKey(0))
                         )[0, p.size:]
        assert got == ref.tolist()


# ---- remote-replica RPC transport --------------------------------------


def test_remote_replica_inprocess_rpc(params):
    """The RPC surface against an in-process peer: heartbeat liveness,
    generate RPC parity, session affinity probes, and the remote shim's
    batcher-stat mirror feeding the front's aggregate stats."""
    peer_eng = _engine(params, 1, seed=0)
    peer = _server(peer_eng)
    httpd = make_http_server(peer, "127.0.0.1", 0)
    host, port = httpd.server_address[:2]
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    front_eng = _engine(params, 1, seed=1)
    front = ServeServer(front_eng, max_active=4, queue_size=16,
                        window_ladder=(1, 4),
                        remote_replicas=(f"http://{host}:{port}",))
    # the RPC shim IS a replica: the router sees two
    assert len(front.replicas) == 2
    assert isinstance(front.replicas[1], RemoteReplica)
    try:
        with peer:
            peer.warmup(prompt_lens=(8,))
            http_thread.start()
            with front:
                front.warmup(prompt_lens=(8,))
                deadline = time.monotonic() + 10
                while (front.replicas[1].batcher.last_heartbeat is None
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                assert front.replicas[1].batcher.last_heartbeat is not None
                h = front.health()
                assert h["replicas_healthy"] == 2
                # pin enough traffic to hit BOTH replicas (fresh requests
                # go least-loaded, round-robin on ties)
                prompts = _prompts(4, seed=4)
                homes, toks, sids = [], [], []
                for p in prompts:
                    r = front.generate(p, max_new_tokens=4,
                                       keep_session=True)
                    homes.append(r.replica)
                    toks.append(list(r.tokens))
                    sids.append(r.session_id)
                assert set(homes) == {0, 1}, homes
                # affinity: continuations land on the session's host
                for i, sid in enumerate(sids):
                    r = front.generate([toks[i][-1]], max_new_tokens=4,
                                       session_id=sid, keep_session=True)
                    assert r.replica == homes[i]
                    toks[i].extend(r.tokens)
                # the aggregate mirrors the remote's counters at the
                # heartbeat cadence — give one poll time to land
                deadline = time.monotonic() + 10
                while (front.stats()["batcher"]["completed"]
                       < len(prompts) * 2
                       and time.monotonic() < deadline):
                    time.sleep(0.1)
                st = front.stats()
                assert st["batcher"]["completed"] >= len(prompts) * 2
                remote_stats = front.replicas[1].batcher.stats()
                assert remote_stats["rpc_completed"] >= 2
                gen = make_generate_fn(_CFG, max_new_tokens=8, greedy=True)
                for p, got in zip(prompts, toks):
                    ref = np.asarray(
                        gen(params, p[None, :], jax.random.PRNGKey(0))
                    )[0, p.size:]
                    assert got == ref.tolist()
    finally:
        httpd.shutdown()
        httpd.server_close()


_HOST_ARGS = [
    "serve", "--http", "--port", "0", "--vocab-size", "31",
    "--hidden-units", "16", "--num-layers", "2", "--seed", "5",
    "--prefill-buckets", "4,8", "--batch-buckets", "1,2",
    "--decode-window", "1", "--prefix-cache", "off",
    "--num-slots", "8", "--max-active", "4",
]


def _boot_host(session_dir, timeout=180.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli",
           *_HOST_ARGS, "--session-dir", session_dir]
    return boot_serve_http_or_raise(cmd, env, timeout)




def test_remote_host_kill_loses_no_kept_session(params):
    """THE 2-process drill (acceptance gate): kept conversations spread
    over a local replica and a remote replica HOST (a real `cli serve
    --http` subprocess) sharing one --session-dir; the host is
    SIGKILLed mid-conversation; every continuation must complete on the
    survivor, token-identical to an uninterrupted run — host death
    generalises PR 7's replica death because the shared disk tier makes
    kept sessions claimable by any host."""
    work = tempfile.mkdtemp(prefix="serve_mesh_hostkill_")
    proc, base = _boot_host(work)
    front = None
    try:
        front_eng = _engine(params, 1, seed=0, session_dir=work)
        front = ServeServer(front_eng, max_active=4, queue_size=16,
                            window_ladder=(1,), remote_replicas=(base,))
        with front:
            front.warmup(prompt_lens=(4,))
            sids, toks, homes = [], [], []
            for i in range(4):
                r = front.generate([i + 1, i + 2, 3], max_new_tokens=4,
                                   keep_session=True, timeout=60)
                sids.append(r.session_id)
                toks.append(list(r.tokens))
                homes.append(r.replica)
            assert 1 in homes, f"nothing routed to the remote: {homes}"
            t_turn = time.time()
            for i, sid in enumerate(sids):
                r = front.generate([toks[i][-1]], max_new_tokens=4,
                                   session_id=sid, keep_session=True,
                                   timeout=60)
                assert r.replica == homes[i]  # affinity crossed the wire
                toks[i].extend(r.tokens)

            # durability boundary: await every session's write-behind
            # checkpoint — file newer than the turn AND quiescent for
            # 1 s, so a lagging previous-boundary write cannot
            # masquerade as the turn's checkpoint — before the crash
            # (the drill tests host DEATH, not an unflushed
            # write-behind)
            deadline = time.time() + 30

            def flushed():
                mtimes = []
                for sid in sids:
                    p = _session_file(work, sid)
                    if not os.path.exists(p):
                        return False
                    mtimes.append(os.path.getmtime(p))
                return (min(mtimes) >= t_turn
                        and time.time() - max(mtimes) > 1.0)

            while not flushed() and time.time() < deadline:
                time.sleep(0.1)
            assert flushed(), "write-behind checkpoints never landed"

            proc.kill()  # SIGKILL: host death, no graceful flush
            proc.wait()

            # zero kept sessions lost: every continuation (including the
            # dead host's) completes on the survivor from the shared tier
            for i, sid in enumerate(sids):
                r = front.generate([toks[i][-1]], max_new_tokens=4,
                                   session_id=sid, keep_session=True,
                                   timeout=60)
                assert r.replica == 0
                toks[i].extend(r.tokens)

            # the heartbeat poller exits and the sweep retires the host
            deadline = time.monotonic() + 15
            while (1 not in front.router.stats()["retired"]
                   and time.monotonic() < deadline):
                front.router.sweep()
                time.sleep(0.2)
            assert 1 in front.router.stats()["retired"]
            assert front.health()["replicas_healthy"] == 1

        # token identity vs the uninterrupted single-replica run
        ref_srv = _server(_engine(params, 1, seed=0), window_ladder=(1,))
        ref = []
        with ref_srv:
            ref_srv.warmup(prompt_lens=(4,))
            rsids = []
            for i in range(4):
                r = ref_srv.generate([i + 1, i + 2, 3], max_new_tokens=4,
                                     keep_session=True)
                rsids.append(r.session_id)
                ref.append(list(r.tokens))
            for _ in range(2):
                for i, sid in enumerate(rsids):
                    r = ref_srv.generate([ref[i][-1]], max_new_tokens=4,
                                         session_id=sid, keep_session=True)
                    ref[i].extend(r.tokens)
        assert toks == ref
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
